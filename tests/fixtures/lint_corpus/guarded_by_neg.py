"""Near-miss negative: every access holds the declared lock, a *_locked
helper relies on the caller-holds-it convention, and an UNannotated
attribute may roam free."""

from cst_captioning_tpu.analysis.locksan import named_lock


class Registry:
    def __init__(self):
        self._lock = named_lock("corpus.registry")
        self._counters = {}  # cstlint: guarded_by=self._lock
        self._sinks = []     # unannotated: not shared, no rule applies

    def inc(self, name):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + 1

    def snapshot(self):
        with self._lock:
            return self._take_locked()

    def _take_locked(self):
        # *_locked convention: the caller holds self._lock.
        return dict(self._counters)

    def add_sink(self, sink):
        self._sinks.append(sink)
