"""Positive: a nested acquisition that inverts the declared LOCK_ORDER,
plus a nesting of unnamed locks neither analysis can check."""

import threading

from cst_captioning_tpu.analysis.locksan import declare_order, named_lock

LOCK_ORDER = ("corpus.outer", "corpus.inner")
declare_order(*LOCK_ORDER)

_OUTER = named_lock("corpus.outer")
_INNER = named_lock("corpus.inner")

_raw_lock_a = threading.Lock()
_raw_lock_b = threading.Lock()


def inverted():
    with _INNER:
        with _OUTER:  # declared outer-before-inner; this is the deadlock
            pass


def anonymous_pair():
    with _raw_lock_a:
        with _raw_lock_b:  # neither lock is named/declared
            pass
