"""Positive: failure-domain code swallowing Exception with only pass."""


def respond(write, payload):
    try:
        write(payload)
    except Exception:
        pass
