"""Positive: per-step device scalar fetch inside a hot-path loop — the
exact pattern PR 3 removed from the trainer's control plane."""


def train_loop(steps, state, step_fn):
    losses = []
    for _ in range(steps):
        state, metrics = step_fn(state)
        losses.append(float(metrics["loss"]))  # fetches a device scalar
    return state, losses
