"""Near-miss negative: nesting that embeds into the declared order
(including transitively), sequential (non-nested) acquisitions, and a
with on a non-lock context manager."""

from cst_captioning_tpu.analysis.locksan import declare_order, named_lock

LOCK_ORDER = ("corpus2.a", "corpus2.b", "corpus2.c")
declare_order(*LOCK_ORDER)

_A = named_lock("corpus2.a")
_B = named_lock("corpus2.b")
_C = named_lock("corpus2.c")


def declared_nesting():
    with _A:
        with _B:
            pass


def transitive_nesting():
    with _A:
        with _C:  # a < c follows from the table
            pass


def sequential_is_free():
    with _B:
        pass
    with _A:  # no lock held: order-free
        pass


def non_lock_context(path):
    with _A:
        with open(path) as f:  # not a lock acquisition
            return f.read()
