"""Positive: deadline construction, polling comparison, and elapsed
arithmetic on the wall clock."""

import time


def wait_for(probe, max_wait_s):
    deadline = time.time() + max_wait_s
    while time.time() < deadline:
        if probe():
            return True
    return False


def elapsed(t0):
    return time.time() - t0
