"""Positive: a literal counter increment with no declare site anywhere
in the project — snapshots can't tell 'armed, 0' from 'absent'."""


def on_retry(registry):
    registry.inc("corpus_orphan_retries")
