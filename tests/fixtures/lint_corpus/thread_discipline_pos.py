"""Positive: anonymous thread, implicit daemonhood, and a non-daemon
thread nobody ever joins."""

import threading


def work():
    pass


def spawn_anonymous():
    threading.Thread(target=work, daemon=True).start()


def spawn_implicit_daemon():
    threading.Thread(target=work, name="worker").start()


def spawn_unreaped():
    t = threading.Thread(target=work, name="leaky", daemon=False)
    t.start()
    # A STRING join must not satisfy the reap-site check — only a
    # Thread-shaped .join() (no args / numeric timeout) counts.
    return ", ".join(str(t) for t in [t])
