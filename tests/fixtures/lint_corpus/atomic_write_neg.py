"""Near-miss negative: json.dumps to a string, a non-JSON text write,
and a read-mode open — none of these is a raw durable-JSON write."""

import json


def render(doc):
    return json.dumps(doc, indent=2)


def save_notes(path, text):
    with open(path + "/notes.txt", "w") as f:
        f.write(text)


def load_summary(path):
    with open(path + "/summary.json") as f:
        return json.load(f)
