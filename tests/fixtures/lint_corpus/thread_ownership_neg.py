"""Near-miss negative: reader threads only touch the inbox; the owner
touches its own state from non-target methods; a closure defined inside
the target (but executed by the scheduler) may touch owned state."""

import queue
import threading


class Server:
    def __init__(self, engine):
        self.engine = engine  # cstlint: owned_by=scheduler
        self._inbox = queue.Queue()

    def run(self):
        def read():
            # Reader thread: parse into the inbox, never the engine.
            for line in iter(input, ""):
                def respond(obj):
                    # Defined inside the target but invoked by the
                    # scheduler loop: owned-state access is legal here.
                    self.engine.note(obj)

                self._inbox.put((line, respond))

        threading.Thread(target=read, name="reader", daemon=True).start()
        self.loop()

    def loop(self):
        # The scheduler loop IS the owner.
        while not self._inbox.empty():
            line, respond = self._inbox.get_nowait()
            self.engine.submit(line)
            respond(line)
