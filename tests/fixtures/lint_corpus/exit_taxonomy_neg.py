"""Near-miss negative: exits through named taxonomy constants, a main()
return value, and a bare re-raise-style exit — all classifiable."""

import sys

from cst_captioning_tpu.resilience.exitcodes import EXIT_USAGE


def main() -> int:
    return 0


def die_typed():
    sys.exit(EXIT_USAGE)


def run():
    sys.exit(main())


def stop():
    sys.exit()
