"""Near-miss negative: the same increments, but declared — one via
registry.declare, one via an engine.COUNTERS-style table that is splat
into declare at attach time."""

COUNTERS = ("corpus_declared_via_table",)


def attach(registry):
    registry.declare("corpus_declared_retries")
    registry.declare(*COUNTERS)


def on_retry(registry):
    registry.inc("corpus_declared_retries")
    registry.inc("corpus_declared_via_table")


def on_dynamic(registry, kind):
    registry.inc(f"corpus_dynamic_{kind}")  # non-literal: out of scope
