"""Near-miss negative: the same program shape, but the step returns the
updated state — the donated input aliases the matching output and the
buffer is genuinely reused in place."""


def build():
    import jax
    import jax.numpy as jnp

    def step(state, x):
        return state + x, jnp.sum(x)

    lowered = jax.jit(step, donate_argnums=(0,)).lower(
        jax.ShapeDtypeStruct((4,), jnp.float32),
        jax.ShapeDtypeStruct((4,), jnp.float32))
    return lowered, 1
