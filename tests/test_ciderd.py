import math

import numpy as np
import pytest

from cst_captioning_tpu.metrics.ciderd import (
    CiderD,
    build_corpus_df,
    load_corpus_df,
    save_corpus_df,
)
from cst_captioning_tpu.metrics.ngrams import precook


def make_scorer(refs):
    df, n = build_corpus_df(refs)
    return CiderD(df_mode="corpus", df=df, ref_len=float(n))


CORPUS = {
    "v1": ["a man is cooking food", "a man cooks in a kitchen", "someone is cooking"],
    "v2": ["a dog runs in a park", "the dog is running outside", "a dog runs fast"],
    "v3": ["a woman sings a song", "the woman is singing", "a lady sings on stage"],
    "v4": ["kids play soccer", "children are playing football", "boys play a ball game"],
}


def test_precook_counts():
    c = precook("a a b")
    assert c[("a",)] == 2 and c[("b",)] == 1 and c[("a", "a")] == 1 and c[("a", "b")] == 1


def test_exact_match_scores_high():
    s = make_scorer(CORPUS)
    res = [{"image_id": "v1", "caption": ["a man is cooking food"]}]
    _, scores = s.compute_score(CORPUS, res)
    # Identical to one ref → strong but <10 (averaged over 3 refs).
    assert scores[0] > 2.0


def test_disjoint_scores_zero():
    s = make_scorer(CORPUS)
    res = [{"image_id": "v1", "caption": ["purple elephants juggle quantum physics"]}]
    _, scores = s.compute_score(CORPUS, res)
    assert scores[0] == pytest.approx(0.0, abs=1e-9)


def test_better_match_scores_higher():
    s = make_scorer(CORPUS)
    good = [{"image_id": "v1", "caption": ["a man is cooking"]}]
    weak = [{"image_id": "v1", "caption": ["a man walks"]}]
    _, g = s.compute_score(CORPUS, good)
    _, w = s.compute_score(CORPUS, weak)
    assert g[0] > w[0]


def test_repetition_clipped():
    # CIDEr-D's clipping: repeating a matched word must not inflate score.
    s = make_scorer(CORPUS)
    normal = [{"image_id": "v2", "caption": ["a dog runs"]}]
    stutter = [{"image_id": "v2", "caption": ["a dog dog dog dog runs"]}]
    _, ns = s.compute_score(CORPUS, normal)
    _, ss = s.compute_score(CORPUS, stutter)
    assert ns[0] > ss[0]


def test_length_penalty():
    # Same content, padded with off-corpus tokens → gaussian length penalty bites.
    s = make_scorer(CORPUS)
    short = [{"image_id": "v2", "caption": ["a dog runs fast"]}]
    long = [{"image_id": "v2", "caption": ["a dog runs fast " + "zz " * 12]}]
    _, sh = s.compute_score(CORPUS, short)
    _, lo = s.compute_score(CORPUS, long)
    assert sh[0] > lo[0]


def test_idf_downweights_common_ngrams():
    # "a" appears in every doc (df=4) → idf 0; a content word appears once → positive.
    df, n = build_corpus_df(CORPUS)
    assert df[("a",)] == 4.0
    assert df[("soccer",)] == 1.0
    log_ref = math.log(4.0)
    assert log_ref - math.log(max(df[("a",)], 1.0)) == pytest.approx(0.0)


def test_batch_order_preserved():
    s = make_scorer(CORPUS)
    res = [
        {"image_id": "v1", "caption": ["a man is cooking"]},
        {"image_id": "v2", "caption": ["a dog runs"]},
        {"image_id": "v1", "caption": ["purple elephants juggle"]},
    ]
    mean, scores = s.compute_score(CORPUS, res)
    assert len(scores) == 3
    assert scores[2] < scores[0]
    assert mean == pytest.approx(scores.mean())


def test_df_pickle_roundtrip(tmp_path):
    df, n = build_corpus_df(CORPUS)
    p = str(tmp_path / "df.pkl")
    save_corpus_df(p, df, n)
    df2, ref_len = load_corpus_df(p)
    assert df2 == df and ref_len == float(n)
    s1 = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    s2 = CiderD(df_mode="corpus", df_path=p)
    res = [{"image_id": "v3", "caption": ["a woman sings"]}]
    assert s1.compute_score(CORPUS, res)[1][0] == pytest.approx(
        s2.compute_score(CORPUS, res)[1][0]
    )


def test_refs_mode_matches_manual_corpus():
    s_corpus = make_scorer(CORPUS)
    s_refs = CiderD(df_mode="refs")
    res = [{"image_id": "v4", "caption": ["kids play football"]}]
    a = s_corpus.compute_score(CORPUS, res)[1][0]
    b = s_refs.compute_score(CORPUS, res)[1][0]
    assert a == pytest.approx(b)


def test_plain_cider_variant():
    # Plain CIDEr: no clipping, no length penalty — stutter & padding hurt
    # less than under CIDEr-D, and matched content scores at least as high.
    d = CiderD(df_mode="refs", variant="cider-d")
    c = CiderD(df_mode="refs", variant="cider")
    long = [{"image_id": "v2", "caption": ["a dog runs fast " + "zz " * 12]}]
    _, d_long = d.compute_score(CORPUS, long)
    _, c_long = c.compute_score(CORPUS, long)
    assert c_long[0] > d_long[0]          # no gaussian penalty
    exact = [{"image_id": "v2", "caption": ["a dog runs fast"]}]
    _, d_e = d.compute_score(CORPUS, exact)
    _, c_e = c.compute_score(CORPUS, exact)
    assert c_e[0] >= d_e[0] - 1e-9


def test_variant_validation():
    with pytest.raises(ValueError):
        CiderD(df_mode="refs", variant="bogus")
