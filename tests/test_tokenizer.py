from cst_captioning_tpu.metrics.tokenizer import tokenize, tokenize_corpus, tokenize_to_str


def test_basic_lowercase_and_split():
    assert tokenize("A man is Cooking.") == ["a", "man", "is", "cooking"]


def test_punctuation_dropped():
    assert tokenize("a dog, a cat; and a bird!") == ["a", "dog", "a", "cat", "and", "a", "bird"]
    assert tokenize("wait... what?") == ["wait", "what"]


def test_contractions_split():
    # PTB splits the suffix off; coco-caption's punctuation filter keeps
    # "'s"/"n't" tokens (only bare "'" is in its removal list).
    assert tokenize("he doesn't stop") == ["he", "does", "n't", "stop"]
    assert tokenize("it's the dog's ball") == ["it", "'s", "the", "dog", "'s", "ball"]
    assert tokenize("they're running") == ["they", "'re", "running"]


def test_special_splits():
    assert tokenize("you cannot win") == ["you", "can", "not", "win"]
    assert tokenize("I'm gonna go") == ["i", "'m", "gon", "na", "go"]


def test_brackets_removed():
    assert tokenize("a man (on a bike) rides") == ["a", "man", "on", "a", "bike", "rides"]


def test_abbreviation_periods_kept():
    # PTB keeps abbreviation-shaped tokens whole, including their periods.
    assert "u.s." in tokenize("made in the u.s.")


def test_mid_caption_sentence_periods_split():
    assert tokenize("A man is cooking. He smiles.") == [
        "a", "man", "is", "cooking", "he", "smiles",
    ]


def test_double_quotes_dropped():
    assert tokenize('the "dog" runs') == ["the", "dog", "runs"]


def test_bare_apostrophes_stripped():
    assert tokenize("the dogs' bones") == ["the", "dogs", "bones"]
    assert tokenize("'hello' there") == ["hello", "there"]
    # ...but contraction tokens keep their apostrophe.
    assert tokenize("the dog's bone") == ["the", "dog", "'s", "bone"]


def test_corpus_shape():
    out = tokenize_corpus({"v1": ["A man runs.", "The man is running"]})
    assert out == {"v1": ["a man runs", "the man is running"]}


def test_empty():
    assert tokenize("") == []
    assert tokenize("...") == []
