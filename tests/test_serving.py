"""Caption-serving engine (ISSUE 8): scheduler core + parity + drills.

Fast slice (tier-1):
- bit-identity of a resident row's caption vs the offline compiled decode
  (greedy, beam, and the fused Pallas decode kernel where available) —
  the engine changes scheduling, never captions;
- deterministic fake-clock scheduler units: FIFO admission, slot reuse,
  bounded-queue shed, drain-on-signal semantics;
- bucket discipline: compile-once program cache, 0 builds under steady
  load after warm(), grow-only bucket migration;
- the offline serve_decode_split twin vs decode_split on a real synthetic
  split (the in-process form of `eval.py --engine serving`);
- the open-loop Poisson probe surface (p50/p99 + captions/s + recompile
  assert).

The subprocess front-end drills (stdin SIGTERM drain -> exit 75, socket
smoke, eval.py --engine serving CLI) are marked `slow` and run via
`make serve-bench`.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.beam import beam_search
from cst_captioning_tpu.ops.sampling import (
    all_finished,
    finished_mask,
    sample_captions,
)
from cst_captioning_tpu.serving.bench import poisson_arrivals, serving_probe
from cst_captioning_tpu.serving.buckets import (
    ProgramCache,
    parse_buckets,
    pick_bucket,
)
from cst_captioning_tpu.serving.engine import ServingEngine, serve_decode_split

V, B, T, D, MAX_LEN = 12, 5, 3, 7, 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def make_variables(model, feats, eos_bias=0.4):
    variables = model.init(jax.random.PRNGKey(0), feats,
                           np.zeros((B, MAX_LEN), np.int32))
    params = {**variables["params"]}
    params["logit"] = {**params["logit"]}
    # Mild EOS bias: one video terminates immediately (frees its slot
    # mid-run, exercising recycling), the rest run full length.
    params["logit"]["bias"] = params["logit"]["bias"].at[0].add(eos_bias)
    return {"params": params}


@pytest.fixture(scope="module")
def setup():
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    feats_np = np.random.default_rng(0).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = make_variables(model, [jnp.asarray(feats_np)])
    return model, variables, feats_np


def submit_all(engine, feats_np):
    for i in range(feats_np.shape[0]):
        assert engine.submit(i, [feats_np[i]])


def tokens_by_id(completions):
    return {c.request_id: c.tokens for c in completions}


# -- the shared per-row finished predicate (satellite 1) -------------------


def test_finished_mask_shapes():
    rows = jnp.asarray([True, False, True])
    np.testing.assert_array_equal(np.asarray(finished_mask(rows)),
                                  [True, False, True])
    beams = jnp.asarray([[True, True], [True, False]])
    np.testing.assert_array_equal(np.asarray(finished_mask(beams)),
                                  [True, False])
    assert not bool(all_finished(beams))
    assert bool(all_finished(jnp.asarray([[True], [True]])))


def test_parse_buckets_and_pick():
    assert parse_buckets("8, 1,4") == (1, 4, 8)
    assert pick_bucket((1, 4, 8), 3) == 4
    assert pick_bucket((1, 4, 8), 99) == 8
    with pytest.raises(ValueError):
        parse_buckets("1,x")
    with pytest.raises(ValueError):
        parse_buckets("")


def test_program_cache_builds_once():
    cache = ProgramCache()
    calls = []
    fn = cache.get(("k",), lambda: calls.append(1) or (lambda: 7))
    assert cache.get(("k",), lambda: pytest.fail("rebuilt")) is fn
    assert cache.builds == 1 and len(calls) == 1


# -- bit-identity vs the offline compiled decode ---------------------------


def test_resident_greedy_caption_bit_identical(setup):
    """Acceptance: a resident row's caption == the offline eval decode,
    bit for bit — with slots smaller than the batch, so rows complete
    while others are mid-flight and freed slots are re-admitted."""
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    submit_all(engine, feats_np)
    got = tokens_by_id(engine.run_until_idle())
    assert sorted(got) == list(range(B))
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(offline))
    stats = engine.stats()
    assert stats["completed"] == B and stats["slots"] == 2


def test_resident_beam_caption_bit_identical(setup):
    model, variables, feats_np = setup
    best, _, _ = beam_search(model, variables, [jnp.asarray(feats_np)],
                             beam_size=3, max_len=MAX_LEN, length_norm=0.7)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           beam_size=3, length_norm=0.7, decode_chunk=2,
                           bucket_sizes=(2,), queue_limit=0)
    submit_all(engine, feats_np)
    got = tokens_by_id(engine.run_until_idle())
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(best))


def test_resident_pallas_caption_bit_identical():
    """Same contract under the fused Pallas decode kernel (PR-6): the
    engine routes through make_decode_step, so --decode_kernel pallas
    must serve the same captions the offline pallas decode produces."""
    pytest.importorskip("jax.experimental.pallas",
                        reason="Pallas unavailable in this jax build")
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0,
                         decode_kernel="pallas")
    feats_np = np.random.default_rng(3).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = make_variables(model, [jnp.asarray(feats_np)])
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    submit_all(engine, feats_np)
    got = tokens_by_id(engine.run_until_idle())
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(offline))


# -- scheduler core (deterministic fake clock) -----------------------------


def test_admission_is_fifo_and_slots_reused(setup):
    model, variables, feats_np = setup
    clock = FakeClock()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,),
                           queue_limit=0, clock=clock)
    for i in range(B):
        engine.submit(i, [feats_np[i]])
        clock.tick(1.0)  # distinct arrival stamps: 0, 1, 2, ...
    comps = []
    while not engine.idle:
        comps.extend(engine.step())
        clock.tick(1.0)
    # FIFO: admission order follows submit order (admit_at nondecreasing
    # in request id), and every slot index stays inside the 2-slot bucket
    # with both slots exercised (reuse after a row finished).
    by_id = sorted(comps, key=lambda c: c.request_id)
    admit_times = [c.admit_at for c in by_id]
    assert admit_times == sorted(admit_times)
    assert {c.slot for c in comps} == {0, 1}
    assert all(c.latency_s == c.done_at - float(c.request_id)
               for c in comps)  # fake-clock latency math is deterministic
    assert engine.stats()["completed"] == B


def test_bounded_queue_sheds_overflow(setup):
    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=2)
    assert engine.submit(0, [feats_np[0]])
    assert engine.submit(1, [feats_np[1]])
    assert not engine.submit(2, [feats_np[2]])      # queue full: shed
    stats = engine.stats()
    assert stats["shed"] == 1 and stats["queue_depth"] == 2
    engine.step()                                   # admits one
    assert engine.submit(3, [feats_np[3]])          # room again
    got = tokens_by_id(engine.run_until_idle())
    assert sorted(got) == [0, 1, 3]                 # 2 was shed, never ran


def test_drain_completes_residents_rejects_queued(setup):
    """The SIGTERM drain contract: in-flight rows finish (bit-identical),
    queued requests come back rejected, the engine ends idle."""
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    submit_all(engine, feats_np)
    first = engine.step()                 # 2 admitted, mid-flight
    done, rejected = engine.drain()
    done = list(first) + done
    assert sorted(c.request_id for c in done) == [0, 1]
    assert [r.request_id for r in rejected] == [2, 3, 4]
    for c in done:
        np.testing.assert_array_equal(c.tokens,
                                      np.asarray(offline)[c.request_id])
    assert engine.idle
    assert engine.stats()["rejected_drain"] == 3


def test_feature_shape_mismatch_rejected(setup):
    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           bucket_sizes=(1,))
    with pytest.raises(ValueError, match="feature shapes"):
        engine.submit(0, [feats_np[0][:, :-1]])


def test_transformer_decoder_rejected():
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0,
                         decoder_type="transformer", num_heads=2,
                         num_tx_layers=1, tx_max_len=MAX_LEN)
    with pytest.raises(ValueError, match="per-row decoder state"):
        ServingEngine(model, {"params": {}}, [(T, D)], max_len=MAX_LEN)


# -- bucket discipline -----------------------------------------------------


def test_zero_builds_under_steady_load_after_warm(setup):
    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1, 2),
                           queue_limit=0)
    warm_builds = engine.warm()["compiles"]
    assert warm_builds == len(engine.buckets)       # one program set each
    for wave in range(2):                           # sustained load
        submit_all(engine, feats_np)
        engine.run_until_idle()
    assert engine.stats()["compiles"] == warm_builds
    assert engine.stats()["completed"] == 2 * B


def test_bucket_grows_to_fit_demand_and_parity_holds(setup):
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1, 4),
                           queue_limit=0)
    engine.submit(0, [feats_np[0]])
    engine.step()                                   # running in bucket 1
    assert engine.stats()["slots"] == 1
    for i in range(1, B):
        engine.submit(i, [feats_np[i]])
    got = tokens_by_id(engine.run_until_idle())
    assert engine.stats()["slots"] == 4             # grew, fixed ladder
    np.testing.assert_array_equal(
        np.stack([got[i] for i in range(B)]), np.asarray(offline))


# -- telemetry -------------------------------------------------------------


def test_engine_registry_counters_and_gauges(setup):
    from cst_captioning_tpu.telemetry.registry import MetricsRegistry

    model, variables, feats_np = setup
    registry = MetricsRegistry()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=3,
                           registry=registry)
    for i in range(B):
        engine.submit(i, [feats_np[i]])
    engine.run_until_idle()
    snap = registry.snapshot()
    assert snap["counters"]["serve_requests"] == B
    assert snap["counters"]["serve_completed"] == B - snap["counters"][
        "serve_shed"]
    assert snap["counters"]["serve_compiles"] >= 1
    assert snap["gauges"]["serve_queue_depth"] == 0
    assert snap["gauges"]["serve_slot_occupancy"] == 0.0
    assert snap["gauges"]["serve_latency_p99_ms"] >= \
        snap["gauges"]["serve_latency_p50_ms"]
    assert snap["histograms"]["serve_admit_ms"]["count"] >= 1
    assert snap["histograms"]["serve_decode_step_ms"]["count"] >= 1


# -- the Poisson probe -----------------------------------------------------


def test_poisson_arrivals_seeded_deterministic():
    a = poisson_arrivals(16, 5.0, seed=9)
    b = poisson_arrivals(16, 5.0, seed=9)
    np.testing.assert_array_equal(a, b)
    assert (np.diff(a) > 0).all()


def test_serving_probe_reports_latency_and_zero_recompiles(setup):
    model, variables, _ = setup
    out = serving_probe(model, variables, [(T, D)],
                        num_requests=6, rate_hz=50.0, max_len=MAX_LEN,
                        decode_chunk=2, bucket_sizes=(1, 2), seed=4)
    assert out["completed"] == 6 and out["shed"] == 0
    assert out["captions_per_sec"] > 0
    assert out["latency_p99_ms"] >= out["latency_p50_ms"] > 0
    assert out["recompiles_after_warmup"] == 0
    assert out["arrival_seed"] == 4 and out["buckets"] == [1, 2]


# -- offline split decode (the eval.py --engine serving core) --------------


@pytest.fixture(scope="module")
def synth_split(tmp_path_factory):
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate

    root = str(tmp_path_factory.mktemp("serve_split"))
    paths = generate(root, "test", SyntheticSpec(
        num_videos=6, captions_per_video=3, max_len=MAX_LEN,
        feat_dims=(16, 8), feat_times=(3, 1)))
    return paths


def _open_split(paths):
    from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths
    from cst_captioning_tpu.data.loader import CaptionLoader

    ds = CaptionDataset(SplitPaths(
        feat_h5=json.loads(paths["feat_h5"]), label_h5=paths["label_h5"],
        info_json=paths["info_json"], cocofmt_json=paths["cocofmt_json"]))
    loader = CaptionLoader(ds, batch_size=4, seq_per_img=1, shuffle=False)
    return ds, loader


@pytest.mark.parametrize("beam_size", (1, 2))
def test_serve_decode_split_matches_legacy(synth_split, beam_size):
    """serve_decode_split == decode_split caption for caption on a real
    (synthetic) split — the in-process twin of the eval.py parity drill,
    covering the loader/batch-slicing/dedupe plumbing around the engine."""
    from cst_captioning_tpu.training.evaluation import decode_split
    from cst_captioning_tpu.training.state import create_train_state, \
        make_optimizer
    from cst_captioning_tpu.training.trainer import build_model
    from cst_captioning_tpu.opts import parse_opts

    ds, loader = _open_split(synth_split)
    try:
        opt = parse_opts(["--rnn_size", "16", "--input_encoding_size", "16",
                          "--att_size", "16", "--drop_prob", "0.0",
                          "--max_length", str(MAX_LEN)])
        model = build_model(opt, ds.vocab.size_with_pad, ds.seq_length)
        tx, _ = make_optimizer()
        state = create_train_state(
            model, jax.random.PRNGKey(0),
            list(zip(ds.feat_times, ds.feat_dims)), ds.seq_length, 1, tx)
        legacy = decode_split(model, state.params, loader, ds.vocab,
                              MAX_LEN, beam_size=beam_size,
                              decode_chunk=2)
        serving = serve_decode_split(model, state.params, loader, ds.vocab,
                                     MAX_LEN, beam_size=beam_size,
                                     decode_chunk=2, bucket_sizes=(1, 4))
        assert serving == legacy
    finally:
        ds.close()


# -- opts satellite: chunk-0 + serving warn-once ---------------------------


def test_warn_once_decode_chunk_zero_with_serving(capsys):
    import cst_captioning_tpu.opts as opts

    opts._warned_serving_chunk = False
    ns = opts.parse_opts(["--engine", "serving", "--decode_chunk", "0"])
    assert ns.engine == "serving"
    err = capsys.readouterr().err
    assert err.count("slot recycling") <= 1
    assert "--decode_chunk 0" in err and "recycling" in err
    opts.parse_opts(["--engine", "serving", "--decode_chunk", "0"])
    assert "recycling" not in capsys.readouterr().err   # warn-once
    # chunked serving (the shipped default) stays silent
    opts._warned_serving_chunk = False
    opts.parse_opts(["--engine", "serving"])
    assert "recycling" not in capsys.readouterr().err


def test_serve_buckets_usage_error():
    from cst_captioning_tpu.opts import parse_opts

    with pytest.raises(SystemExit) as exc:
        parse_opts(["--serve_buckets", "1,frog"])
    assert exc.value.code == 2                      # one-line usage error


# -- slow subprocess drills (make serve-bench) -----------------------------


def _spawn_serve(extra, stdin=subprocess.PIPE):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "serve.py"),
         "--serve_demo", "1", "--beam_size", "1", "--max_length", "8",
         "--loglevel", "WARNING"] + extra,
        stdin=stdin, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, cwd=REPO, env=env)


@pytest.mark.slow
def test_serve_cli_stdin_and_sigterm_drain():
    """The end-to-end drain drill: demo server answers requests, SIGTERM
    under load drains in-flight, rejects queued, exits 75 (preempted /
    resumable in the exit-code taxonomy)."""
    from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED, \
        classify

    proc = _spawn_serve([])
    try:
        for i in range(3):
            proc.stdin.write(json.dumps({"id": i, "video_id": f"v{i}"})
                             + "\n")
        proc.stdin.write('{"id": 9, "video_id": "bogus"}\n')
        proc.stdin.flush()
        replies = [json.loads(proc.stdout.readline()) for _ in range(4)]
        by_id = {r["id"]: r for r in replies}
        assert by_id[9]["error"] == "unknown_video"
        for i in range(3):
            assert "caption" in by_id[i] and by_id[i]["latency_ms"] >= 0
        # now load it up and SIGTERM mid-flight
        for i in range(10, 30):
            proc.stdin.write(json.dumps({"id": i, "video_id":
                                         f"v{i % 8}"}) + "\n")
        proc.stdin.flush()
        time.sleep(0.3)                     # let a few admissions land
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == EXIT_PREEMPTED, err
        assert classify(proc.returncode) == "resumable"
        tail = [json.loads(l) for l in out.splitlines() if l.strip()]
        assert any(r.get("error") == "rejected_draining" for r in tail) \
            or all("caption" in r for r in tail)  # tiny race: all may finish
        assert "drained" in err
    finally:
        proc.kill()


@pytest.mark.slow
def test_serve_cli_socket_smoke():
    proc = _spawn_serve(["--serve_port", "-1"], stdin=subprocess.DEVNULL)
    try:
        port = None
        deadline = time.time() + 90
        while time.time() < deadline:
            line = proc.stderr.readline()
            if "listening on 127.0.0.1:" in line:
                port = int(line.rsplit(":", 1)[1])
                break
        assert port, "server never announced its port"
        with socket.create_connection(("127.0.0.1", port), timeout=60) as s:
            s.sendall(b'{"id": 1, "video_id": "v5"}\n')
            f = s.makefile("r")
            reply = json.loads(f.readline())
        assert reply["id"] == 1 and "caption" in reply
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED

        assert proc.returncode == EXIT_PREEMPTED
    finally:
        proc.kill()


@pytest.mark.slow
def test_eval_cli_engine_serving_parity(synth_split, tmp_path):
    """eval.py --engine serving end to end: train nothing (random params
    would need a checkpoint) — instead run the CLI against a checkpoint
    produced by one tiny XE epoch, asserting it exits 0 (the in-CLI
    parity assert is the test) and writes scores."""
    from cst_captioning_tpu.data.synthetic import SyntheticSpec, generate

    root = str(tmp_path)
    spec = SyntheticSpec(num_videos=6, captions_per_video=3,
                         max_len=MAX_LEN, feat_dims=(16, 8),
                         feat_times=(3, 1))
    train = generate(root, "train", spec)
    from cst_captioning_tpu.data.vocab import load_vocab

    vocab = load_vocab(train["vocab_json"])
    test = generate(root, "test", spec, vocab=vocab)
    ckpt = os.path.join(root, "ckpt")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    common = ["--rnn_size", "16", "--input_encoding_size", "16",
              "--att_size", "16", "--drop_prob", "0.0",
              "--max_length", str(MAX_LEN), "--batch_size", "4",
              "--loglevel", "WARNING"]
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "train.py"),
         "--train_feat_h5"] + json.loads(train["feat_h5"]) + [
         "--train_label_h5", train["label_h5"],
         "--train_info_json", train["info_json"],
         "--train_cocofmt_file", train["cocofmt_json"],
         "--val_feat_h5"] + json.loads(test["feat_h5"]) + [
         "--val_label_h5", test["label_h5"],
         "--val_info_json", test["info_json"],
         "--val_cocofmt_file", test["cocofmt_json"],
         "--checkpoint_path", ckpt, "--max_epochs", "1",
         "--seq_per_img", "2", "--fast_val", "1"] + common,
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert rc.returncode == 0, rc.stderr[-2000:]
    result = os.path.join(root, "scores.json")
    # INFO: the "serving-engine parity" log line is part of the assertion.
    eval_common = [a for a in common if a not in ("--loglevel", "WARNING")] \
        + ["--loglevel", "INFO"]
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "eval.py"),
         "--checkpoint_path", ckpt, "--engine", "serving",
         "--test_feat_h5"] + json.loads(test["feat_h5"]) + [
         "--test_label_h5", test["label_h5"],
         "--test_info_json", test["info_json"],
         "--test_cocofmt_file", test["cocofmt_json"],
         "--beam_size", "2", "--result_file", result] + eval_common,
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600)
    assert rc.returncode == 0, rc.stderr[-2000:]
    assert "serving-engine parity" in rc.stderr
    assert os.path.exists(result)
