"""Preemption layer units: exit-code taxonomy, signal-flag handler,
harness classification, and the doc tables pinned to the code.

The tier-1-safe slice of ISSUE 4: everything here runs in-process in
milliseconds (no jax backend, no subprocess training).  The end-to-end
drills — real SIGTERM through the train.py CLI, boundary save, taxonomy
exit, bit-exact resume — live in tests/test_resilience.py
(``TestPreemptionEndToEnd``, marked ``slow``; ``make chaos`` runs them).
"""

import importlib.util
import os
import signal
import sys
import threading

import pytest

from cst_captioning_tpu.resilience import exitcodes
from cst_captioning_tpu.resilience.exitcodes import (
    EXIT_ADVANTAGE_ABORT,
    EXIT_PREEMPTED,
    EXIT_WEDGE,
    classify,
    describe,
    normalize,
)
from cst_captioning_tpu.resilience.preemption import (
    PreemptedExit,
    PreemptionHandler,
)

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- exit-code taxonomy ----------------------------------------------------

class TestExitcodeTaxonomy:
    def test_catalogued_codes_classify(self):
        assert classify(0) == "ok"
        assert classify(1) == "fatal"
        assert classify(2) == "fatal"                 # argparse usage
        assert classify(EXIT_ADVANTAGE_ABORT) == "fatal"
        assert classify(EXIT_PREEMPTED) == "resumable"
        assert classify(EXIT_WEDGE) == "wedge"
        assert classify(130) == "fatal"               # operator Ctrl-C
        assert classify(137) == "resumable"           # external SIGKILL
        assert classify(143) == "resumable"           # unhandled SIGTERM

    def test_negative_subprocess_form_normalizes(self):
        """subprocess reports death-by-signal as -signum; the shell as
        128+signum.  Both spellings of one death must classify alike."""
        assert normalize(-signal.SIGTERM) == 143
        assert normalize(-signal.SIGKILL) == 137
        assert classify(-signal.SIGTERM) == classify(143)
        assert classify(-signal.SIGSEGV) == "resumable"  # external kill

    def test_uncatalogued_codes(self):
        # Died to an uncatalogued signal: proves nothing about the stage.
        assert classify(128 + signal.SIGSEGV) == "resumable"
        assert classify(128 + signal.SIGBUS) == "resumable"
        # Ordinary unknown exits: surface, never auto-retry.
        assert classify(3) == "fatal"
        assert classify(77) == "fatal"
        assert classify(255) == "fatal"

    def test_constants_are_catalogued_and_consistent(self):
        """Every importable EXIT_* constant must appear in CODES with the
        category classify() reports — the table IS the taxonomy."""
        for name, rc in vars(exitcodes).items():
            if name.startswith("EXIT_"):
                assert rc in exitcodes.CODES, f"{name} missing from CODES"
                assert classify(rc) == exitcodes.CODES[rc].category

    def test_describe_is_human_one_liner(self):
        assert "preempted" in describe(EXIT_PREEMPTED)
        assert "\n" not in describe(EXIT_PREEMPTED)
        assert "resumable" in describe(150)       # uncatalogued signal
        assert "fatal" in describe(77)
        assert "signal" in describe(-11)


# -- the signal-flag handler -----------------------------------------------

class TestPreemptionHandler:
    def test_sigterm_sets_flag_and_counts(self):
        h = PreemptionHandler().install()
        try:
            assert not h.requested
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested
            assert h.signal_name == "SIGTERM"
            assert h.signal_monotonic is not None
            assert h.drain_signal_count() == 1
            assert h.drain_signal_count() == 0, "drain must be incremental"
            # Repeated TERMs during the grace window are absorbed, counted.
            os.kill(os.getpid(), signal.SIGTERM)
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested
            assert h.drain_signal_count() == 2
        finally:
            h.uninstall()

    def test_uninstall_restores_previous_dispositions(self):
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        h = PreemptionHandler().install()
        assert signal.getsignal(signal.SIGTERM) == h._handle
        h.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev_term
        assert signal.getsignal(signal.SIGINT) == prev_int
        h.uninstall()  # idempotent

    def test_first_sigint_is_graceful_second_is_hard(self):
        """Interactive contract: the FIRST Ctrl-C requests the graceful
        checkpoint-and-exit; the handler then hands SIGINT back to the
        previous disposition so a second Ctrl-C stops the run hard."""
        prev_int = signal.getsignal(signal.SIGINT)
        h = PreemptionHandler().install()
        try:
            os.kill(os.getpid(), signal.SIGINT)
            assert h.requested and h.signal_name == "SIGINT"
            # The next SIGINT now goes to the PREVIOUS handler, not ours.
            assert signal.getsignal(signal.SIGINT) == prev_int
        finally:
            h.uninstall()
        assert signal.getsignal(signal.SIGINT) == prev_int

    def test_install_off_main_thread_is_safe_noop(self):
        h = PreemptionHandler()
        before = signal.getsignal(signal.SIGTERM)
        t = threading.Thread(target=h.install)
        t.start()
        t.join()
        assert signal.getsignal(signal.SIGTERM) == before
        h.uninstall()

    def test_preempted_exit_carries_the_story(self):
        e = PreemptedExit(42, "SIGTERM", True)
        assert e.step == 42 and e.saved and e.signal_name == "SIGTERM"
        assert "step 42" in str(e) and "saved" in str(e)
        assert "already current" in str(PreemptedExit(7, "SIGINT", False))


# -- registry declare (rare-event counters visible at 0) -------------------

class TestDeclaredCounters:
    def test_declare_registers_zero_without_resetting(self):
        from cst_captioning_tpu.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        reg.declare("preempt_signals", "preempt_saves")
        assert reg.counter("preempt_saves") == 0
        reg.inc("preempt_signals", 3)
        reg.declare("preempt_signals")  # re-declare must NOT reset
        assert reg.counter("preempt_signals") == 3
        snap = reg.snapshot()
        assert snap["counters"]["preempt_saves"] == 0
        assert snap["counters"]["preempt_signals"] == 3
        hb = reg.heartbeat_payload()
        assert hb["counters"]["preempt_saves"] == 0


# -- harness classification (scale_chain.run_stage) ------------------------

def _load_scale_chain():
    spec = importlib.util.spec_from_file_location(
        "scale_chain", os.path.join(REPO, "scripts", "scale_chain.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _cpu_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    return env


PREEMPT_ONCE = """\
import json, os, sys
stage = sys.argv[1]
os.makedirs(os.path.join(stage, "recovery"), exist_ok=True)
steps = [e for e in os.listdir(os.path.join(stage, "recovery"))
         if e.isdigit()]
if not steps:
    # first attempt: "preempted" — checkpoint advanced, exit resumable
    os.makedirs(os.path.join(stage, "recovery", "5"))
    with open(os.path.join(stage, "infos.json"), "w") as f:
        json.dump({"last_step": 5}, f)
    sys.exit(75)
sys.exit(0)
"""


class TestRunStageTaxonomy:
    def test_preempt_exit_restarts_without_probe_or_attempt_burn(
            self, tmp_path, capsys):
        """A 75 with an advanced fingerprint counts as PROGRESS: no device
        probe, no no-progress attempt consumed, immediate restart."""
        sc = _load_scale_chain()
        script = tmp_path / "preempt_once.py"
        script.write_text(PREEMPT_ONCE)
        stage = tmp_path / "stage"
        stage.mkdir()
        events = []

        class Log:
            def emit(self, event, **fields):
                events.append({"event": event, **fields})

        # max_attempts=1: if the preempt exit consumed a no-progress
        # attempt, the SECOND pass would hit the cap and abort — finishing
        # proves the checkpoint-advanced restart is free.
        sc.run_stage("pre", [sys.executable, str(script), str(stage)],
                     max_attempts=1, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                     probe_timeout_s=20.0, env=_cpu_env(),
                     fingerprint=sc.stage_fingerprint(str(stage)),
                     events=Log())
        kinds = [e["event"] for e in events]
        assert "resumable_exit" in kinds
        assert "probe" not in kinds, "resumable exits must not device-probe"
        assert "stage_done" in kinds
        res = next(e for e in events if e["event"] == "resumable_exit")
        assert res["rc"] == 75 and res["preempted"] and res["progressed"]
        out = capsys.readouterr().out
        assert "resumable exit rc=75" in out

    def test_repeated_preempt_without_progress_hits_cap(self, tmp_path):
        """A stage that exits 75 forever WITHOUT advancing its checkpoint
        (pathological) must still be bounded by the no-progress cap, not
        loop eternally."""
        sc = _load_scale_chain()
        script = tmp_path / "always75.py"
        script.write_text("import sys; sys.exit(75)\n")
        # The cap's diagnosis must name what the attempts died OF (an
        # exit-at-startup loop), not the wedge/--wedge_timeout story —
        # the resumable path never probed the device.
        with pytest.raises(SystemExit,
                           match="no on-disk progress.*exited resumable"):
            sc.run_stage("pre75", [sys.executable, str(script)],
                         max_attempts=2, wedge_poll_s=0.1,
                         max_wedge_wait_s=30.0, probe_timeout_s=20.0,
                         env=_cpu_env())

    def test_external_sigterm_death_is_retried_as_resumable(self, tmp_path):
        """143 (SIGTERM death without the graceful handler — eval stages,
        or a kill during unwinding) resumes from checkpoint instead of
        aborting as a real failure."""
        sc = _load_scale_chain()
        script = tmp_path / "term_once.py"
        marker = tmp_path / "attempted"
        script.write_text(
            "import os, signal, sys\n"
            "m = sys.argv[1]\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close()\n"
            "    signal.signal(signal.SIGTERM, signal.SIG_DFL)\n"
            "    os.kill(os.getpid(), signal.SIGTERM)\n"
            "sys.exit(0)\n")
        sc.run_stage("term", [sys.executable, str(script), str(marker)],
                     max_attempts=3, wedge_poll_s=0.1, max_wedge_wait_s=30.0,
                     probe_timeout_s=20.0, env=_cpu_env())
        assert marker.exists()

    def test_fatal_codes_still_abort(self, tmp_path):
        """The taxonomy must not soften real failures: an advantage abort
        (4) aborts the chain on a healthy device, exactly like 1/2."""
        sc = _load_scale_chain()
        script = tmp_path / "abort4.py"
        script.write_text("import sys; sys.exit(4)\n")
        with pytest.raises(SystemExit, match="real failure"):
            sc.run_stage("adv", [sys.executable, str(script)],
                         max_attempts=3, wedge_poll_s=0.1,
                         max_wedge_wait_s=30.0, probe_timeout_s=20.0,
                         env=_cpu_env())


# -- harness e2e: scale_chain rides through a real preemption --------------

@pytest.mark.e2e
@pytest.mark.slow
def test_scale_chain_rides_through_preemption(tmp_path):
    """The whole loop at the harness level: a micro chain whose XE stage
    is preempted by a real SIGTERM (`preempt@step=0`) must be restarted by
    scale_chain as a resumable exit — no device probe, no abort — and the
    chain must complete with the stage's full step count on disk."""
    import json
    import subprocess

    from conftest import CACHE_DIR

    env = _cpu_env()
    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    out = tmp_path / "chain"
    proc = subprocess.run(
        [sys.executable, "scripts/scale_chain.py", "--out_dir", str(out),
         "--stages", "xe",
         "--num_videos", "6", "--num_val", "4", "--batch_size", "2",
         "--rnn_size", "32", "--rich_vocab", "60",
         "--feat_dims", "16", "16", "--feat_times", "4", "1",
         "--xe_epochs", "1", "--patience", "0",
         "--max_stage_attempts", "6",
         "--fault_plan", "preempt@step=0"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-3000:]}\n"
        f"stderr:{proc.stderr[-3000:]}")
    assert "resumable exit rc=75" in proc.stdout

    events = [json.loads(line) for line in
              (out / "chain_events.jsonl").read_text().splitlines()]
    resumable = [e for e in events if e["event"] == "resumable_exit"]
    assert resumable and resumable[0]["rc"] == 75 and resumable[0]["preempted"]
    # The preempt exit's boundary save registered as on-disk progress.
    assert resumable[0]["progressed"]
    assert "stage_done" in [e["event"] for e in events]
    # No stage_abort: the preemption never read as a real failure.
    assert not [e for e in events if e["event"] == "stage_abort"]
    with open(out / "checkpoints" / "xe" / "infos.json") as f:
        assert json.load(f)["last_step"] == 3  # 6 videos / batch 2 x 1 epoch


# -- docs pinned to the code -----------------------------------------------

class TestDocsStayInSync:
    def test_resilience_md_exit_code_table_matches_codes(self):
        """RESILIENCE.md's exit-code table is sourced from
        exitcodes.CODES: every catalogued code must appear with its name
        and classification, so docs and taxonomy cannot drift."""
        with open(os.path.join(REPO, "RESILIENCE.md")) as f:
            doc = f.read()
        for rc, code in exitcodes.CODES.items():
            assert f"`{rc}`" in doc, f"exit code {rc} missing from doc table"
            assert code.name in doc, f"{code.name} missing from doc table"

    def test_resilience_md_documents_preemption(self):
        with open(os.path.join(REPO, "RESILIENCE.md")) as f:
            doc = f.read()
        assert "preempt@step=" in doc, "fault grammar must list preempt"
        assert "preemption" in doc.lower()
        assert "--save_interval_secs" in doc
        assert "skip_batches" in doc, "deterministic-resume note missing"
