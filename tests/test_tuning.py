"""Autotuner (cst_captioning_tpu/tuning/): record, resolution, sweep.

Pins the ISSUE-6 contracts:

- record writes are per-platform merges — a CPU sweep can NEVER overwrite
  a TPU entry;
- resolution order is explicit flag > tuning record > built-in default,
  with auditable provenance on the namespace;
- the sweep is deterministic and resumable: a complete record at the same
  git SHA + identity is reused with ZERO re-measurement, a partial record
  resumes measuring only the missing points;
- a run whose config came from the record is bit-identical to the same
  config passed as explicit flags (the record changes WHERE values come
  from, never what they mean);
- opts validators and the overlap-under-device-rewards warning.
"""

import argparse
import io
import json
import os
import sys

import jax
import numpy as np
import pytest

from cst_captioning_tpu import opts as opts_mod
from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.tuning import record as record_mod
from cst_captioning_tpu.tuning import sweep as sweep_mod
from cst_captioning_tpu.tuning.record import (
    load_record,
    platform_entry,
    resolve_platform,
    resolved_tuned_defaults,
    save_platform_entry,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_entry(platform="cpu", complete=True, sha="deadbeef", **winner):
    axes = {"decode_chunk": 4, "scan_unroll": 2, "overlap_rewards": 1,
            "device_rewards": 1, "decode_kernel": "pallas",
            "bench_batch_size": 64}
    axes.update(winner)
    return {"platform": platform, "git_sha": sha, "complete": complete,
            "measured_at": "2026-08-04 00:00:00", "winner": axes,
            "winner_captions_per_sec": 111.0, "points": []}


# -- record persistence ----------------------------------------------------


class TestRecord:
    def test_per_platform_merge_never_clobbers(self, tmp_path):
        """The satellite invariant: a cpu save must preserve the tpu entry
        byte-for-byte (and vice versa)."""
        path = str(tmp_path / "rec.json")
        save_platform_entry(make_entry("tpu", decode_chunk=16), path)
        save_platform_entry(make_entry("cpu", decode_chunk=4), path)
        doc = load_record(path)
        assert set(doc["platforms"]) == {"tpu", "cpu"}
        assert doc["platforms"]["tpu"]["winner"]["decode_chunk"] == 16
        assert doc["platforms"]["cpu"]["winner"]["decode_chunk"] == 4
        # overwrite of the SAME platform is allowed
        save_platform_entry(make_entry("cpu", decode_chunk=8), path)
        assert platform_entry("cpu", path)["winner"]["decode_chunk"] == 8
        assert platform_entry("tpu", path)["winner"]["decode_chunk"] == 16

    def test_entry_requires_platform_key(self, tmp_path):
        with pytest.raises(ValueError, match="platform"):
            save_platform_entry({"winner": {}}, str(tmp_path / "r.json"))

    def test_missing_and_torn_records_degrade_to_empty(self, tmp_path):
        assert load_record(str(tmp_path / "nope.json"))["platforms"] == {}
        torn = tmp_path / "torn.json"
        torn.write_text('{"version": 1, "platfo')
        assert load_record(str(torn))["platforms"] == {}

    def test_resolve_platform_env_wins(self, tmp_path, monkeypatch):
        path = str(tmp_path / "rec.json")
        save_platform_entry(make_entry("tpu"), path)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        assert resolve_platform(path) == "cpu"
        # without the env pin, a device entry beats cpu
        monkeypatch.setenv("JAX_PLATFORMS", "")
        save_platform_entry(make_entry("cpu"), path)
        assert resolve_platform(path) == "tpu"

    def test_incomplete_entry_is_not_applied(self, tmp_path, monkeypatch):
        path = str(tmp_path / "rec.json")
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        save_platform_entry(make_entry("cpu", complete=False), path)
        tuned, prov = resolved_tuned_defaults(path=path)
        assert tuned == {} and prov is None

    def test_invalid_record_values_dropped_with_warning(self, tmp_path,
                                                        monkeypatch,
                                                        capsys):
        """A hand-edited/corrupt record must not smuggle in values the
        CLI validators would reject (scan_unroll=0 would crash deep in
        lax.scan): invalid axes fall back to built-ins, loudly."""
        path = str(tmp_path / "rec.json")
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        save_platform_entry(make_entry(
            "cpu", scan_unroll=0, decode_chunk="8",
            decode_kernel="mosaic", device_rewards=3), path)
        tuned, prov = resolved_tuned_defaults(path=path)
        # only the valid axis (overlap_rewards=1 from make_entry) survives
        assert tuned == {"overlap_rewards": 1}
        err = capsys.readouterr().err
        for axis in ("scan_unroll", "decode_chunk", "decode_kernel",
                     "device_rewards"):
            assert f"invalid {axis}" in err

    def test_applied_axes_exclude_informational_keys(self, tmp_path,
                                                     monkeypatch):
        """bench_batch_size is recorded but never applied to a run."""
        path = str(tmp_path / "rec.json")
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        save_platform_entry(make_entry("cpu"), path)
        tuned, prov = resolved_tuned_defaults(path=path)
        assert "bench_batch_size" not in tuned
        assert set(tuned) <= set(record_mod.TUNABLE_AXES)
        assert prov["platform"] == "cpu"
        assert prov["git_sha_matches_head"] is False  # "deadbeef" != HEAD


# -- opts resolution -------------------------------------------------------


class TestOptsResolution:
    @pytest.fixture()
    def rec(self, tmp_path, monkeypatch):
        path = str(tmp_path / "rec.json")
        monkeypatch.setenv("CST_TUNED_CONFIGS", path)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        save_platform_entry(make_entry("cpu"), path)
        return path

    def test_record_fills_unset_axes(self, rec):
        ns = parse_opts([])
        assert ns.decode_chunk == 4
        assert ns.scan_unroll == 2
        assert ns.decode_kernel == "pallas"
        assert ns.overlap_rewards == 1
        assert ns.tuned_provenance["tuned"] is True
        assert ns.tuned_provenance["record"] == rec
        assert set(ns.tuned_provenance["applied"]) == set(
            record_mod.TUNABLE_AXES)
        json.dumps(ns.tuned_provenance)  # must survive infos.json

    def test_explicit_flag_always_wins(self, rec):
        ns = parse_opts(["--decode_chunk", "16", "--decode_kernel",
                         "reference"])
        assert ns.decode_chunk == 16
        assert ns.decode_kernel == "reference"
        assert ns.scan_unroll == 2  # still tuned
        applied = ns.tuned_provenance["applied"]
        assert "decode_chunk" not in applied
        assert "decode_kernel" not in applied
        assert "scan_unroll" in applied

    def test_disabled_resolution_keeps_builtins(self, monkeypatch):
        monkeypatch.setenv("CST_TUNED_CONFIGS", "")
        ns = parse_opts([])
        from cst_captioning_tpu.opts import (
            DEFAULT_DECODE_CHUNK,
            DEFAULT_SCAN_UNROLL,
        )

        assert ns.decode_chunk == DEFAULT_DECODE_CHUNK
        assert ns.scan_unroll == DEFAULT_SCAN_UNROLL
        assert ns.decode_kernel == "reference"
        assert ns.tuned_provenance == {"tuned": False}

    def test_validators_usage_errors(self, monkeypatch):
        monkeypatch.setenv("CST_TUNED_CONFIGS", "")
        for bad in (["--scan_unroll", "0"], ["--scan_unroll", "-2"],
                    ["--scan_unroll", "x"], ["--decode_chunk", "-1"],
                    ["--decode_chunk", "y"]):
            with pytest.raises(SystemExit) as e:
                parse_opts(bad)
            assert e.value.code == 2, bad
        # legal boundary values parse
        assert parse_opts(["--decode_chunk", "0"]).decode_chunk == 0
        assert parse_opts(["--scan_unroll", "1"]).scan_unroll == 1

    def test_overlap_under_device_rewards_warns_once(self, monkeypatch,
                                                     capsys):
        monkeypatch.setenv("CST_TUNED_CONFIGS", "")
        monkeypatch.setattr(opts_mod, "_warned_overlap_ignored", False)
        parse_opts(["--overlap_rewards", "3", "--device_rewards", "1"])
        parse_opts(["--overlap_rewards", "3", "--device_rewards", "1"])
        err = capsys.readouterr().err
        assert err.count("--overlap_rewards is ignored") == 1
        # host path: no warning
        monkeypatch.setattr(opts_mod, "_warned_overlap_ignored", False)
        parse_opts(["--overlap_rewards", "3", "--device_rewards", "0"])
        assert "ignored" not in capsys.readouterr().err


# -- bench integration -----------------------------------------------------


def _bench():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


class TestBenchResolution:
    def _args(self, **kw):
        base = dict(batch_size=2, seq_per_img=2, seq_len=8, vocab=60,
                    hidden=16, bfloat16=0, native_cider=0,
                    decode_chunk=None, scan_unroll=None, decode_kernel=None,
                    overlap_depth=None, device_rewards=None)
        base.update(kw)
        return argparse.Namespace(**base)

    def test_tuned_fields_false_without_record(self, monkeypatch):
        monkeypatch.setenv("CST_TUNED_CONFIGS", "")
        bench = _bench()
        fields = bench.tuning_fields(self._args())
        assert fields == {"tuned": False, "tuning_record": None}

    def test_axes_resolve_from_record_and_flags_win(self, tmp_path,
                                                    monkeypatch):
        path = str(tmp_path / "rec.json")
        monkeypatch.setenv("CST_TUNED_CONFIGS", path)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        save_platform_entry(make_entry("cpu"), path)
        bench = _bench()
        axes, sources, _ = bench.resolve_axes(self._args())
        assert axes["decode_chunk"] == 4
        assert sources["decode_chunk"] == "record"
        assert axes["decode_kernel"] == "pallas"
        fields = bench.tuning_fields(self._args())
        assert fields["tuned"] is True
        assert fields["tuning_record"] == path
        assert fields["tuned_axes"]["scan_unroll"] == 2
        # an explicit flag beats the record AND flips its source label
        axes2, sources2, _ = bench.resolve_axes(self._args(decode_chunk=16))
        assert axes2["decode_chunk"] == 16
        assert sources2["decode_chunk"] == "flag"
        # all-flags run is NOT tuned even with a record present
        fields2 = bench.tuning_fields(self._args(
            decode_chunk=4, scan_unroll=2, decode_kernel="pallas",
            overlap_depth=1, device_rewards=1))
        assert fields2["tuned"] is False

    def test_resolved_config_identity_tuned_equals_explicit(self, tmp_path,
                                                            monkeypatch):
        """The bench cache identity of a tuned-default run equals the same
        config passed as explicit flags — they ARE the same measurement."""
        path = str(tmp_path / "rec.json")
        monkeypatch.setenv("CST_TUNED_CONFIGS", path)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        save_platform_entry(make_entry("cpu"), path)
        bench = _bench()
        tuned_cfg = bench.resolved_config(self._args())
        explicit_cfg = bench.resolved_config(self._args(
            decode_chunk=4, scan_unroll=2, decode_kernel="pallas",
            overlap_depth=1, device_rewards=1))
        assert tuned_cfg == explicit_cfg
        assert tuned_cfg["decode_kernel"] == "pallas"
        assert tuned_cfg["scan_unroll"] == 2


# -- sweep (the `make tune-fast` smoke, riding in tier-1) ------------------


TINY = dict(batch_size=2, seq_per_img=2, seq_len=8, vocab=60, hidden=16,
            steps=2, bfloat16=0, native_cider=0)


class TestSweep:
    def test_space_is_deterministic(self):
        base = sweep_mod.base_namespace(**TINY)
        assert sweep_mod.sweep_space(base, fast=True) == \
            sweep_mod.sweep_space(base, fast=True)
        full = sweep_mod.sweep_space(base, fast=False)
        assert full == sweep_mod.sweep_space(base, fast=False)
        # the full grid covers every axis value at least once
        kernels = {p["decode_kernel"] for p in full}
        assert kernels == {"reference", "pallas", "bf16"}
        assert {p["device_rewards"] for p in full} == {0, 1}
        assert {p["scan_unroll"] for p in full} >= {1, 2}
        assert len({p["batch_size"] for p in full}) == 2

    def test_fast_sweep_measures_persists_reuses_resumes(self, tmp_path,
                                                         monkeypatch):
        """The acceptance drill: sweep -> complete record; rerun -> reused
        with zero measurements; damaged/partial record -> resume measures
        ONLY the missing points; cpu entry never touches a tpu entry."""
        path = str(tmp_path / "rec.json")
        save_platform_entry(make_entry("tpu"), path)  # must survive
        base = sweep_mod.base_namespace(**TINY)

        n0 = sweep_mod.MEASUREMENTS
        entry, reused = sweep_mod.run_sweep(base, fast=True,
                                            record_path=path)
        assert not reused
        assert sweep_mod.MEASUREMENTS - n0 == 2
        assert entry["platform"] == "cpu"
        assert entry["complete"] is True
        assert len(entry["points"]) == 2
        assert entry["winner"]["device_rewards"] == 1
        assert entry["winner_captions_per_sec"] > 0
        assert set(entry["winner"]) == set(record_mod.TUNABLE_AXES) | \
            {"bench_batch_size"}

        # rerun on the unchanged tree: reused, not re-measured
        entry2, reused2 = sweep_mod.run_sweep(base, fast=True,
                                              record_path=path)
        assert reused2 and sweep_mod.MEASUREMENTS - n0 == 2
        assert entry2 == entry

        # partial record resumes: only the dropped point re-measures
        doc = load_record(path)
        doc["platforms"]["cpu"]["complete"] = False
        doc["platforms"]["cpu"]["points"] = \
            doc["platforms"]["cpu"]["points"][:1]
        from cst_captioning_tpu.resilience.integrity import atomic_json_write

        atomic_json_write(path, doc)
        entry3, reused3 = sweep_mod.run_sweep(base, fast=True,
                                              record_path=path)
        assert not reused3
        assert sweep_mod.MEASUREMENTS - n0 == 3  # exactly one more
        assert entry3["complete"] is True

        # the TPU entry was never touched by any of the cpu writes
        assert platform_entry("tpu", path) == make_entry("tpu")

        # the record resolves end-to-end through parse_opts
        monkeypatch.setenv("CST_TUNED_CONFIGS", path)
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        ns = parse_opts([])
        assert ns.tuned_provenance["tuned"] is True
        for axis, value in ns.tuned_provenance["applied"].items():
            assert getattr(ns, axis) == value == entry3["winner"][axis]

    def test_identity_mismatch_restarts_not_resumes(self, tmp_path,
                                                    monkeypatch):
        """Stale points (other shapes/steps/code) must not mix into a
        fresh sweep: a changed identity re-measures everything.  Uses a
        stub measurer — the real harness is covered by the smoke test
        above; this pins the identity/restart LOGIC without paying four
        more compiles of tier-1 wall."""
        calls = []

        def fake_measure(base, cfg):
            calls.append(dict(cfg))
            return {"config": dict(cfg),
                    "captions_per_sec": 100.0 + len(calls),
                    "path": "device_fused"}

        monkeypatch.setattr(sweep_mod, "measure_point", fake_measure)
        path = str(tmp_path / "rec.json")
        base = sweep_mod.base_namespace(**TINY)
        sweep_mod.run_sweep(base, fast=True, record_path=path)
        assert len(calls) == 2
        other = sweep_mod.base_namespace(**{**TINY, "steps": 3})
        sweep_mod.run_sweep(other, fast=True, record_path=path)
        assert len(calls) == 4

    def test_winner_tie_breaks_deterministically(self):
        points = [
            {"config": {"decode_chunk": 0}, "captions_per_sec": 5.0},
            {"config": {"decode_chunk": 8}, "captions_per_sec": 5.0},
            {"config": {"decode_chunk": 4}, "captions_per_sec": None},
        ]
        assert sweep_mod.pick_winner(points)["config"]["decode_chunk"] == 0
        assert sweep_mod.pick_winner(
            [{"config": {}, "captions_per_sec": None}]) is None

    def test_winner_ignores_other_batch_sizes(self):
        """The 2x-batch probe point reports more captions/s from batch
        alone; it must never decide the tuned axes (review finding)."""
        points = [
            {"config": {"decode_chunk": 16, "batch_size": 32},
             "captions_per_sec": 10.0},
            {"config": {"decode_chunk": 8, "batch_size": 64},
             "captions_per_sec": 19.0},
        ]
        win = sweep_mod.pick_winner(points, batch_size=32)
        assert win["config"]["decode_chunk"] == 16

    def test_resume_remeasures_errored_points(self, tmp_path, monkeypatch):
        """A transiently-failed point in a PARTIAL record must be
        re-measured on resume, not baked into the final record."""
        calls = []

        def fake_measure(base, cfg):
            calls.append(dict(cfg))
            return {"config": dict(cfg), "captions_per_sec": 50.0,
                    "path": "device_fused"}

        monkeypatch.setattr(sweep_mod, "measure_point", fake_measure)
        path = str(tmp_path / "rec.json")
        base = sweep_mod.base_namespace(**TINY)
        space = sweep_mod.sweep_space(base, fast=True)
        from cst_captioning_tpu.utils.platform import git_head_sha

        save_platform_entry({
            "platform": "cpu", "git_sha": git_head_sha(REPO),
            "sweep": sweep_mod.sweep_identity(base, True),
            "complete": False,
            "points": [
                {"config": dict(space[0]), "captions_per_sec": 100.0,
                 "path": "device_fused"},
                {"config": dict(space[1]), "captions_per_sec": None,
                 "path": None, "error": "transient"},
            ],
        }, path)
        entry, reused = sweep_mod.run_sweep(base, fast=True,
                                            record_path=path)
        assert not reused
        assert calls == [space[1]]  # only the errored point re-measured
        assert all(p["captions_per_sec"] is not None
                   for p in entry["points"])


# -- tuned-config run == explicit-flag run, bit for bit --------------------


def test_tuned_decode_bit_identical_to_explicit_flags(tmp_path, monkeypatch):
    """Acceptance criterion: a run whose decode config came from the
    tuning record produces bit-identical decode outputs to the same
    config passed as explicit flags — resolution changes provenance,
    never computation."""
    from cst_captioning_tpu.ops.sampling import sample_captions
    from cst_captioning_tpu.training.trainer import build_model

    path = str(tmp_path / "rec.json")
    monkeypatch.setenv("CST_TUNED_CONFIGS", path)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    save_platform_entry(make_entry("cpu", decode_chunk=3, scan_unroll=2,
                                   decode_kernel="pallas"), path)
    tuned_ns = parse_opts(["--rnn_size", "16", "--input_encoding_size",
                           "16", "--att_size", "16"])
    explicit_ns = parse_opts([
        "--rnn_size", "16", "--input_encoding_size", "16",
        "--att_size", "16", "--decode_chunk", "3", "--scan_unroll", "2",
        "--decode_kernel", "pallas", "--overlap_rewards", "1",
        "--device_rewards", "1"])
    assert tuned_ns.tuned_provenance["tuned"] is True
    # all-explicit run: nothing applied -> not a tuned run
    assert explicit_ns.tuned_provenance == {"tuned": False}

    feats = [jax.random.normal(jax.random.PRNGKey(1), (3, 4, 8))]
    outs = []
    for ns in (tuned_ns, explicit_ns):
        model = build_model(ns, vocab_size=30, seq_length=8)
        variables = model.init(jax.random.PRNGKey(0), feats,
                               np.zeros((3, 8), np.int32))
        toks, logps = sample_captions(
            model, variables, feats, jax.random.PRNGKey(7), 8,
            seq_per_img=2, greedy=False, decode_chunk=ns.decode_chunk)
        outs.append((np.asarray(toks), np.asarray(logps)))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])


# -- telemetry provenance --------------------------------------------------


def test_registry_meta_rides_into_snapshot(tmp_path):
    from cst_captioning_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry()
    prov = {"tuned": True, "applied": {"decode_chunk": 4},
            "record": "/x/TUNED_CONFIGS.json"}
    reg.set_meta("tuned_config", prov)
    snap = reg.snapshot()
    assert snap["meta"]["tuned_config"] == prov
    path = str(tmp_path / "telemetry.json")
    reg.write_snapshot(path)
    with open(path) as f:
        assert json.load(f)["meta"]["tuned_config"]["tuned"] is True


# -- report script ---------------------------------------------------------


def test_tune_report_prints_table(tmp_path, monkeypatch, capsys):
    path = str(tmp_path / "rec.json")
    entry = make_entry("cpu")
    entry["points"] = [
        {"config": {"decode_chunk": 4, "scan_unroll": 2,
                    "overlap_rewards": 1, "device_rewards": 1,
                    "decode_kernel": "pallas", "batch_size": 64},
         "captions_per_sec": 111.0, "path": "device_fused"},
        {"config": {"decode_chunk": 0, "scan_unroll": 1,
                    "overlap_rewards": 1, "device_rewards": 1,
                    "decode_kernel": "reference", "batch_size": 64},
         "captions_per_sec": None, "path": None, "error": "boom"},
    ]
    save_platform_entry(entry, path)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import tune_report
    finally:
        sys.path.pop(0)
    monkeypatch.setattr(sys, "argv", ["tune_report.py", "--record", path])
    assert tune_report.main() == 0
    out = capsys.readouterr().out
    assert "*WINNER*" in out
    assert "failed" in out and "boom" in out
    assert "complete" in out
