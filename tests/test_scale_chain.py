"""End-to-end drive of the scale-chain harness's MAIN path.

test_watchdog.py covers run_stage's recovery logic in isolation; this
file runs the actual ``scripts/scale_chain.py`` CLI at micro scale —
synthesize → one XE epoch → beam eval — and then checks that
``scripts/chain_report.py`` turns the run into a status + curves + beam
report.  The harness that must carry the north-star evidence unattended
must itself be exercised in CI (VERDICT r4, weak #2): its arg plumbing,
dataset reuse, event log, and report path all run here.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_chain_report():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import chain_report
    finally:
        sys.path.pop(0)
    return chain_report


def _cpu_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    from conftest import CACHE_DIR

    env.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
    return env


MICRO = [
    "--num_videos", "6", "--num_val", "4", "--batch_size", "2",
    "--rnn_size", "32", "--rich_vocab", "60",
    "--feat_dims", "16", "16", "--feat_times", "4", "1",
    "--xe_epochs", "1", "--patience", "0",
]


@pytest.mark.e2e
def test_scale_chain_main_micro(tmp_path):
    out = tmp_path / "chain"
    env = _cpu_env()
    proc = subprocess.run(
        [sys.executable, "scripts/scale_chain.py", "--out_dir", str(out),
         "--stages", "xe,eval", *MICRO],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstdout:{proc.stdout[-3000:]}\n"
        f"stderr:{proc.stderr[-3000:]}")

    # The stage trained and left real evidence on disk.
    infos_path = out / "checkpoints" / "xe" / "infos.json"
    with open(infos_path) as f:
        infos = json.load(f)
    assert infos["last_step"] > 0
    assert (out / "checkpoints" / "xe" / "metrics.jsonl").exists()
    assert (out / "xe_beam5.json").exists()

    # The event log recorded the lifecycle.
    events = [json.loads(line)
              for line in (out / "chain_events.jsonl").read_text().splitlines()]
    kinds = [e["event"] for e in events]
    for expected in ("chain_start", "dataset_ready", "stage_start",
                     "attempt_start", "stage_done", "chain_done"):
        assert expected in kinds, f"missing {expected} in {kinds}"

    # Re-invoking with the same spec reuses the dataset (no regeneration).
    proc2 = subprocess.run(
        [sys.executable, "scripts/scale_chain.py", "--out_dir", str(out),
         "--stages", "eval", *MICRO],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    assert proc2.returncode == 0, proc2.stdout[-2000:] + proc2.stderr[-2000:]
    assert "reusing dataset" in proc2.stdout

    # chain_report reads it all back: status, curve table, beam table.
    rj = out / "report.json"
    rep = subprocess.run(
        [sys.executable, "scripts/chain_report.py", "--out_dir", str(out),
         "--json", str(rj)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120)
    assert rep.returncode == 0, rep.stderr[-2000:]
    assert "Chain status" in rep.stdout
    assert "complete" in rep.stdout
    report = json.loads(rj.read_text())
    assert report["status"]["state"] == "complete"
    assert report["curves"]["xe"], "xe val curve missing from report"
    assert "xe" in report["beam"] and "CIDEr" in report["beam"]["xe"]

    # collect_evidence snapshots the durable pieces with a manifest.
    dest = tmp_path / "artifacts"
    col = subprocess.run(
        [sys.executable, "scripts/collect_evidence.py", "--out_dir",
         str(out), "--name", "micro", "--dest", str(dest)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert col.returncode == 0, col.stderr[-2000:]
    man = json.loads((dest / "micro" / "MANIFEST.json").read_text())
    assert man["report_rc"] == 0
    assert "scale_chain.py" in (man["regen_command"] or "")
    for rel in ("xe/metrics.jsonl", "xe_beam5.json", "report.json",
                "chain_events.jsonl"):
        assert (dest / "micro" / rel).exists(), f"missing {rel}"
        assert rel in man["files"]


def test_chain_report_explains_blocked_chain(tmp_path):
    """A chain that has produced NO curves must still be explainable:
    the report derives 'wedged since when, how many probes' from the
    event log instead of printing an empty table (VERDICT r4, weak #1)."""
    chain_report = _import_chain_report()
    out = tmp_path / "blocked"
    out.mkdir()
    t0 = 1000.0
    events = [
        {"ts": t0, "event": "chain_start", "argv": [], "stages": "xe"},
        {"ts": t0 + 1, "event": "dataset_ready"},
        {"ts": t0 + 2, "event": "stage_start", "tag": "xe"},
        {"ts": t0 + 3, "event": "attempt_start", "tag": "xe", "attempt": 1},
        {"ts": t0 + 100, "event": "attempt_exit", "tag": "xe", "attempt": 1,
         "rc": 124, "timed_out": False, "progressed": False},
        {"ts": t0 + 101, "event": "wedge", "tag": "xe", "rc": 124},
        {"ts": t0 + 200, "event": "probe", "tag": "xe", "verdict": "wedged"},
        {"ts": t0 + 300, "event": "probe", "tag": "xe", "verdict": "wedged"},
    ]
    with open(out / "chain_events.jsonl", "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")

    status = chain_report.chain_status(
        chain_report.load_events(str(out)), now=t0 + 400)
    assert status["state"] == "wedged"
    assert status["stage"] == "xe"
    assert status["age_s"] == pytest.approx(299, abs=2)
    xe = status["stages"]["xe"]
    assert xe["wedges"] == 1 and xe["probes_since_wedge"] == 2

    # A later chain_start supersedes the wedged history.
    with open(out / "chain_events.jsonl", "a") as f:
        f.write(json.dumps({"ts": t0 + 500, "event": "chain_start",
                            "argv": [], "stages": "xe"}) + "\n")
        f.write(json.dumps({"ts": t0 + 501, "event": "chain_done",
                            "stages": "xe"}) + "\n")
    status2 = chain_report.chain_status(
        chain_report.load_events(str(out)), now=t0 + 502)
    assert status2["state"] == "complete"


def test_chain_status_heal_and_abort_states():
    """The status fold distinguishes healing (device back, stage about to
    resume) from wedged, and an abort pins its reason to the stage."""
    chain_report = _import_chain_report()
    t0 = 2000.0
    base = [
        {"ts": t0, "event": "chain_start", "argv": [], "stages": "xe"},
        {"ts": t0 + 1, "event": "stage_start", "tag": "xe"},
        {"ts": t0 + 2, "event": "attempt_start", "tag": "xe", "attempt": 1},
        {"ts": t0 + 50, "event": "wedge", "tag": "xe", "rc": 124},
        {"ts": t0 + 100, "event": "probe", "tag": "xe", "verdict": "wedged"},
        {"ts": t0 + 200, "event": "probe", "tag": "xe", "verdict": "ok"},
        {"ts": t0 + 201, "event": "healed", "tag": "xe", "waited_s": 151.0},
    ]
    st = chain_report.chain_status(base, now=t0 + 230)
    assert st["state"] == "healing" and st["stage"] == "xe"

    aborted = base + [
        {"ts": t0 + 300, "event": "attempt_start", "tag": "xe", "attempt": 2},
        {"ts": t0 + 400, "event": "stage_abort", "tag": "xe",
         "reason": "no_progress_cap"},
    ]
    st2 = chain_report.chain_status(aborted, now=t0 + 500)
    assert st2["state"] == "aborted"
    assert st2["stages"]["xe"]["abort"] == "no_progress_cap"
    assert "no_progress_cap" in st2["detail"]


def test_chain_report_parses_console_log_fallback(tmp_path):
    """Chains started before the event log existed (the live r4b chain)
    are still diagnosable from their console markers."""
    chain_report = _import_chain_report()
    log = tmp_path / "chain.log"
    log.write_text(
        "reusing dataset in /tmp/x/data\n"
        "=== stage: xe ===\n"
        "WATCHDOG: no progress for 1500s (timeout 1500s)\n"
        "=== xe: wedge (rc=124); polling for the device every 180s ===\n"
        "=== xe: device probe detail: device probe timed out after 120s ===\n"
    )
    st = chain_report.log_status(str(log))
    assert st["state"] == "wedged"
    assert st["stage"] == "xe"
    assert st["counts"]["wedge"] == 1
    assert "timed out" in st["probe_details"][0]

    # A resume attempt alone (stage not yet done) already means the
    # device healed — the chain is running, not wedged.
    log.write_text(log.read_text() +
                   "=== xe: attempt 2 (resume; 0 healthy...) ===\n")
    st2 = chain_report.log_status(str(log))
    assert st2["state"] == "running"
    assert st2["counts"]["attempt"] == 1

    log.write_text(log.read_text() + "=== xe done: best 3.2 @ step 40 ===\n")
    st3 = chain_report.log_status(str(log))
    assert st3["state"] == "running" and st3["counts"]["done"] == 1


def test_compare_bundles_reads_committed_artifacts():
    """The cross-bundle ladder table renders from the committed
    artifacts/ bundles (and any new ones) without error."""
    proc = subprocess.run(
        [sys.executable, "scripts/compare_bundles.py"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "Evidence ladder" in proc.stdout
    # Every committed bundle appears as a row.
    for name in ("probe64", "mid128", "cpu512"):
        assert f"| {name} |" in proc.stdout
    # probe64's known xe val best renders in its cell.
    assert "0.5032" in proc.stdout


def test_event_log_edge_cases(tmp_path):
    """The event log is evidence infrastructure: it must never kill the
    harness (unwritable path -> silent no-op), must append well-formed
    JSON lines, and load_events must skip a torn tail line (killed
    harness mid-write) without losing the rest."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "scale_chain", os.path.join(REPO, "scripts", "scale_chain.py"))
    scale_chain = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(scale_chain)
    chain_report = _import_chain_report()

    # Disabled log (path None): every emit is a no-op.
    scale_chain.EventLog(None).emit("chain_start", argv=[])

    # Unwritable path: swallowed, harness survives.
    bad = scale_chain.EventLog(str(tmp_path / "no" / "such" / "dir" / "e.jsonl"))
    bad.emit("chain_start", argv=[])

    # Normal appends round-trip through load_events...
    out = tmp_path / "run"
    out.mkdir()
    log = scale_chain.EventLog(str(out / "chain_events.jsonl"))
    log.emit("chain_start", argv=["--x"], stages="xe")
    log.emit("stage_start", tag="xe")
    # ...and a torn tail (SIGKILL mid-write) is skipped, not fatal.
    with open(out / "chain_events.jsonl", "a") as f:
        f.write('{"ts": 1, "event": "attempt_st')
    events = chain_report.load_events(str(out))
    assert [e["event"] for e in events] == ["chain_start", "stage_start"]
    status = chain_report.chain_status(events, now=events[-1]["ts"] + 10.0)
    assert status["state"] == "running" and status["stage"] == "xe"
    assert status["last_event_age_s"] == pytest.approx(10.0, abs=1.0)


def test_collect_evidence_survives_report_timeout(tmp_path, monkeypatch):
    """A wedged/killed chain_report must not leave a provenance-less
    bundle: collect_evidence still writes MANIFEST.json, recording the
    failure as a nonzero report_rc (round-5 advisor)."""
    src = tmp_path / "run"
    src.mkdir()
    (src / "chain_events.jsonl").write_text(
        json.dumps({"event": "chain_start", "argv": ["--num_videos", "6"]})
        + "\n")
    dest = tmp_path / "artifacts"

    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import collect_evidence
    finally:
        sys.path.pop(0)

    def boom(*a, **k):
        raise subprocess.TimeoutExpired(cmd="chain_report", timeout=300)

    monkeypatch.setattr(collect_evidence.subprocess, "run", boom)
    monkeypatch.setattr(sys, "argv", [
        "collect_evidence.py", "--out_dir", str(src), "--name", "probe",
        "--dest", str(dest)])
    assert collect_evidence.main() == 0

    with open(dest / "probe" / "MANIFEST.json") as f:
        manifest = json.load(f)
    assert manifest["report_rc"] == 124
    assert "chain_events.jsonl" in manifest["files"]
    assert "report.json" not in manifest["files"]
    assert "scale_chain.py" in manifest["regen_command"]
