"""Dataset-format converters -> prepro annotation contract."""

import json

import pytest

from cst_captioning_tpu.data.converters import (
    convert_activitynet,
    convert_msrvtt,
    convert_msvd,
)
from cst_captioning_tpu.data.prepro import build_split


class TestMSRVTT:
    def _blob(self):
        return {
            "videos": [
                {"video_id": "video0", "split": "train"},
                {"video_id": "video1", "split": "validate"},
                {"video_id": "video2", "split": "test"},
            ],
            "sentences": [
                {"video_id": "video0", "caption": "a man is cooking"},
                {"video_id": "video0", "caption": "someone cooks food"},
                {"video_id": "video1", "caption": "a dog runs"},
                {"video_id": "video2", "caption": "a cat sleeps"},
            ],
        }

    def test_split_routing(self):
        out = convert_msrvtt(self._blob())
        assert [v["id"] for v in out["train"]] == ["video0"]
        assert [v["id"] for v in out["val"]] == ["video1"]
        assert [v["id"] for v in out["test"]] == ["video2"]
        assert len(out["train"][0]["captions"]) == 2

    def test_feeds_prepro(self, tmp_path):
        out = convert_msrvtt(self._blob())
        paths = build_split(out["train"], str(tmp_path), "train", max_len=8)
        assert json.load(open(paths["info_json"]))["videos"] == [{"id": "video0"}]


class TestMSVD:
    # public MSVD caption files are tab-separated; spaces must work too
    LINES = [f"vid{i}\tcaption number {i}\n" for i in range(20)] + [
        "vid0 another caption for clip zero\n", "", "   \n",
    ]

    def test_official_splits(self):
        out = convert_msvd(
            self.LINES,
            splits={"train": ["vid0", "vid1"], "test": ["vid2"]},
        )
        assert {v["id"] for v in out["train"]} == {"vid0", "vid1"}
        assert len([c for v in out["train"] if v["id"] == "vid0"
                    for c in v["captions"]]) == 2

    def test_proportional_split_deterministic(self):
        a = convert_msvd(self.LINES)
        b = convert_msvd(self.LINES)
        assert a == b
        total = sum(len(a[s]) for s in ("train", "val", "test"))
        assert total == 20
        assert len(a["train"]) == 12  # int(20 * 1200/1970)
        assert len(a["val"]) == 1     # int(20 * 100/1970)


class TestActivityNet:
    def test_convert(self):
        out = convert_activitynet({
            "train": {"v_abc": {"sentences": [" A man runs. ", "He jumps."]}},
            "val": {"v_def": {"sentences": ["A dog barks."]}},
        })
        assert out["train"][0]["id"] == "v_abc"
        assert out["train"][0]["captions"] == ["A man runs.", "He jumps."]
        assert out["val"][0]["captions"] == ["A dog barks."]
