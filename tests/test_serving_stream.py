"""Serving latency floor (ISSUE 12): streaming + exact-result cache.

Fast slice (tier-1):
- PREFIX CONSISTENCY: the concatenation of a streamed request's chunks
  is bit-identical to its final caption — greedy (per-chunk emission)
  and beam (one terminal chunk at harvest, the honest formulation);
- streaming telemetry: TTFT / inter-chunk-gap percentiles on a fake
  clock, the `serve_stream_chunks` counter, wire format through the
  in-process CaptionServer (chunk lines strictly before the final);
- the exact-result cache: a hit is bit-identical to the cold decode and
  provably skips encoder+decode (serve_admitted / chunk_dispatches
  unmoved), LRU eviction at capacity, identity-key changes (beam /
  decode_chunk / params) force a miss, per-request no_cache bypass;
- the `serve_cache@req=N` chaos drill through the PR 9 recovery plane:
  the injected lookup failure is absorbed (counted, health degraded)
  and the caption stays bit-identical to the fault-free twin;
- the zipfian Poisson probe fast slice (`make serve-stream-bench`'s API
  twin): hit rate, drill parity record, prefix check, 0 recompiles;
- scripts/serve_report.py renders the new rows and exits 1 on a
  hit/miss-twin mismatch or a cache run that loses to its off twin;
- opts warn-once: `{"op": "stream"}` meeting --decode_chunk 0.
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.sampling import sample_captions
from cst_captioning_tpu.ops.beam import beam_search
from cst_captioning_tpu.resilience.faults import FaultPlan
from cst_captioning_tpu.serving.bench import serving_probe, zipfian_mix
from cst_captioning_tpu.serving.cache import (
    ResultCache,
    feature_fingerprint,
)
from cst_captioning_tpu.serving.engine import ServingEngine, _trim_eos
from cst_captioning_tpu.serving.server import CaptionServer
from cst_captioning_tpu.telemetry.registry import MetricsRegistry

V, B, T, D, MAX_LEN = 12, 5, 3, 7, 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """ISSUE 11 discipline: the serving fast slice runs sanitizer-armed,
    so the new ``serving.result_cache`` leaf lock is runtime-validated
    (no nesting, no inversions) under every streaming/cache test."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists(), (
        f"lock sanitizer receipt from a child process: "
        f"{receipt.read_text()}")


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt=1.0):
        self.t += dt


def make_variables(model, feats, eos_bias=0.4):
    variables = model.init(jax.random.PRNGKey(0), feats,
                           np.zeros((B, MAX_LEN), np.int32))
    params = {**variables["params"]}
    params["logit"] = {**params["logit"]}
    params["logit"]["bias"] = params["logit"]["bias"].at[0].add(eos_bias)
    return {"params": params}


@pytest.fixture(scope="module")
def setup():
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    feats_np = np.random.default_rng(0).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = make_variables(model, [jnp.asarray(feats_np)])
    return model, variables, feats_np


def run_streamed(engine, ids):
    comps, chunks = [], {}
    while not engine.idle:
        comps.extend(engine.step())
        for ch in engine.pop_stream_chunks():
            chunks.setdefault(ch.request_id, []).append(ch)
    return {c.request_id: c for c in comps}, chunks


# -- prefix consistency (the streaming acceptance bar) ---------------------


def test_greedy_stream_prefix_consistent(setup):
    """Concatenating a streamed request's chunks reproduces the final
    caption bit for bit — and the final caption is the offline decode."""
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0)
    for i in range(B):
        assert engine.submit(i, [feats_np[i]], stream=True)
    comps, chunks = run_streamed(engine, range(B))
    assert sorted(comps) == list(range(B))
    multi = 0
    for i in range(B):
        np.testing.assert_array_equal(comps[i].tokens,
                                      np.asarray(offline)[i])
        got = chunks.get(i, [])
        assert [c.seq for c in got] == list(range(len(got)))
        cat = (np.concatenate([c.tokens for c in got])
               if got else np.zeros((0,), np.int32))
        np.testing.assert_array_equal(cat, _trim_eos(comps[i].tokens))
        assert comps[i].stream_chunks == len(got)
        multi += len(got) > 1
    # The fixture's mild EOS bias leaves most captions running several
    # chunks — the test must prove real incremental emission, not just
    # the degenerate one-chunk case.
    assert multi >= 1
    # No chunk ever carries an EOS/pad 0.
    assert all((c.tokens != 0).all() for lst in chunks.values()
               for c in lst)


def test_beam_stream_single_terminal_chunk(setup):
    """Beam cannot stream honestly, so a streamed beam request emits
    EXACTLY one terminal chunk whose tokens are the backtracked winner."""
    model, variables, feats_np = setup
    best, _, _ = beam_search(model, variables, [jnp.asarray(feats_np)],
                             beam_size=3, max_len=MAX_LEN, length_norm=0.7)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           beam_size=3, length_norm=0.7, decode_chunk=2,
                           bucket_sizes=(2,), queue_limit=0)
    for i in range(B):
        assert engine.submit(i, [feats_np[i]], stream=True)
    comps, chunks = run_streamed(engine, range(B))
    for i in range(B):
        np.testing.assert_array_equal(comps[i].tokens, np.asarray(best)[i])
        got = chunks.get(i, [])
        assert len(got) <= 1          # one terminal chunk (0 if empty)
        cat = (got[0].tokens if got else np.zeros((0,), np.int32))
        np.testing.assert_array_equal(cat, _trim_eos(comps[i].tokens))


def test_stream_ttft_and_gap_metrics_fake_clock(setup):
    """TTFT = first-chunk emission minus arrival; gaps between chunk
    emissions — deterministic on the fake clock, and exported through
    stats() and the registry histograms."""
    model, variables, feats_np = setup
    registry = MetricsRegistry()
    clock = FakeClock()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           registry=registry, clock=clock)
    engine.submit(0, [feats_np[1]], stream=True)   # row 1: runs full length
    clock.tick(3.0)
    done = []
    while not engine.idle:
        done.extend(engine.step())
        clock.tick(1.0)
    comp = done[0]
    assert comp.stream_chunks >= 2
    # Arrival at t=0; the scheduler ran its first chunk at t=3.
    assert comp.ttft_s == pytest.approx(3.0)
    stats = engine.stats()
    assert stats["stream_chunks"] == comp.stream_chunks
    assert stats["ttft_p50_ms"] == pytest.approx(3000.0)
    assert stats["chunk_gap_p50_ms"] == pytest.approx(1000.0)
    snap = registry.snapshot()
    assert snap["counters"]["serve_stream_chunks"] == comp.stream_chunks
    assert snap["histograms"]["serve_ttft_ms"]["count"] == 1
    assert snap["histograms"]["serve_chunk_gap_ms"]["count"] == \
        comp.stream_chunks - 1


# -- wire format through the in-process server -----------------------------


def test_server_stream_wire_format(setup):
    model, variables, feats_np = setup
    from cst_captioning_tpu.data.vocab import Vocab

    vocab = Vocab({i: f"w{i}" for i in range(1, V)})
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           result_cache=ResultCache(4))
    out = io.StringIO()
    server = CaptionServer(engine, vocab,
                           lambda vid: [feats_np[int(vid)]], out=out)
    rc = server.run_stdin([json.dumps({"id": 1, "video_id": "1",
                                       "op": "stream"}),
                           json.dumps({"id": 2, "video_id": "2"})])
    assert rc == 0
    replies = [json.loads(l) for l in out.getvalue().splitlines()]
    mine = [r for r in replies if r["id"] == 1]
    final = mine[-1]
    # Chunk lines strictly precede the final; seq is contiguous.
    assert final.get("final") is True and final.get("stream") is True
    parts = mine[:-1]
    assert all(r["stream"] and r["final"] is False for r in parts)
    assert [r["seq"] for r in parts] == list(range(len(parts)))
    assert final["chunks"] == len(parts)
    assert "ttft_ms" in final or not parts
    # Text fragments concatenate to the caption; token concat matches.
    assert " ".join(r["text"] for r in parts if r["text"]) == \
        final["caption"]
    # The plain (non-stream) request keeps the historical shape.
    plain = [r for r in replies if r["id"] == 2][-1]
    assert "stream" not in plain and "caption" in plain

    # Second server on the SAME engine: the repeat is now a cache hit —
    # flagged on the wire, still streaming one terminal chunk.
    out2 = io.StringIO()
    server2 = CaptionServer(engine, vocab,
                            lambda vid: [feats_np[int(vid)]], out=out2)
    rc = server2.run_stdin([json.dumps({"id": 3, "video_id": "1",
                                        "op": "stream"})])
    assert rc == 0
    replies2 = [json.loads(l) for l in out2.getvalue().splitlines()]
    final2 = replies2[-1]
    assert final2.get("cached") is True and final2["final"] is True
    assert final2["caption"] == final["caption"]
    assert final2["decode_steps"] == 0
    chunks2 = [r for r in replies2 if r.get("stream") and not r["final"]]
    assert len(chunks2) <= 1
    if chunks2:
        assert " ".join([chunks2[0]["text"]]) == final2["caption"]


def test_warn_once_stream_with_decode_chunk_zero(setup, capsys):
    """Satellite: {"op": "stream"} traffic meeting --decode_chunk 0 warns
    ONCE, naming the degenerate behavior and the fix."""
    import cst_captioning_tpu.opts as opts

    model, variables, feats_np = setup
    from cst_captioning_tpu.data.vocab import Vocab

    vocab = Vocab({i: f"w{i}" for i in range(1, V)})
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=0, bucket_sizes=(2,), queue_limit=0)
    assert engine.chunk == MAX_LEN                  # legacy one-shot scan
    opts._warned_stream_legacy = False
    server = CaptionServer(engine, vocab,
                           lambda vid: [feats_np[int(vid)]],
                           out=io.StringIO())
    server.run_stdin([json.dumps({"id": 1, "video_id": "0",
                                  "op": "stream"}),
                      json.dumps({"id": 2, "video_id": "1",
                                  "op": "stream"})])
    err = capsys.readouterr().err
    assert err.count("degenerates to one terminal chunk") == 1  # warn-once
    assert "--decode_chunk" in err                  # names the fix
    # chunked engines stay silent
    opts._warned_stream_legacy = False
    engine2 = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                            decode_chunk=2, bucket_sizes=(2,),
                            queue_limit=0)
    server2 = CaptionServer(engine2, vocab,
                            lambda vid: [feats_np[int(vid)]],
                            out=io.StringIO())
    server2.run_stdin([json.dumps({"id": 1, "video_id": "0",
                                   "op": "stream"})])
    assert "degenerates" not in capsys.readouterr().err


# -- the exact-result cache ------------------------------------------------


def test_cache_hit_bit_identical_and_skips_programs(setup):
    """Acceptance: a hit returns the cold decode's caption bit for bit
    and pays ZERO admissions and ZERO chunk dispatches — asserted via
    the existing registry counter + the engine's dispatch count."""
    model, variables, feats_np = setup
    registry = MetricsRegistry()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           result_cache=ResultCache(8), registry=registry)
    # Declared at 0 before any traffic.
    snap0 = registry.snapshot()["counters"]
    for name in ("serve_cache_hits", "serve_cache_misses",
                 "serve_cache_evictions", "serve_cache_bypass",
                 "serve_cache_errors", "serve_stream_chunks"):
        assert snap0[name] == 0
    for i in range(B):
        engine.submit(i, [feats_np[i]])
    cold = {c.request_id: c for c in engine.run_until_idle()}
    s1 = engine.stats()
    assert s1["cache_misses"] == B and s1["cache_hits"] == 0
    admitted1 = registry.snapshot()["counters"]["serve_admitted"]
    d1 = s1["chunk_dispatches"]
    # Second wave: every request hits.
    for i in range(B):
        engine.submit(100 + i, [feats_np[i]])
    warm = {c.request_id: c for c in engine.run_until_idle()}
    s2 = engine.stats()
    assert s2["cache_hits"] == B
    assert s2["chunk_dispatches"] == d1                 # zero decode work
    assert registry.snapshot()["counters"]["serve_admitted"] == admitted1
    for i in range(B):
        comp = warm[100 + i]
        assert comp.cache_hit and comp.decode_steps == 0
        np.testing.assert_array_equal(comp.tokens, cold[i].tokens)
    assert s2["completed"] == 2 * B


def test_cache_lru_eviction_at_capacity(setup):
    model, variables, feats_np = setup
    cache = ResultCache(2)
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           result_cache=cache)
    for i in range(3):                       # fills 0, 1; decoding 2
        engine.submit(i, [feats_np[i]])      # evicts 0 (LRU)
        engine.run_until_idle()
    s = engine.stats()
    assert s["cache_evictions"] == 1 and s["cache_entries"] == 2
    engine.submit(10, [feats_np[0]])         # evicted: miss again
    engine.run_until_idle()
    assert engine.stats()["cache_misses"] == 4
    engine.submit(11, [feats_np[2]])         # still resident: hit
    engine.run_until_idle()
    assert engine.stats()["cache_hits"] == 1


def test_cache_identity_change_forces_miss(setup):
    """A shared cache never crosses configurations: beam width,
    decode_chunk (the bench cache-config identity), or a params change
    each key a different entry; the same configuration hits."""
    model, variables, feats_np = setup
    cache = ResultCache(32)

    def eng(**kw):
        base = dict(max_len=MAX_LEN, decode_chunk=2, bucket_sizes=(2,),
                    queue_limit=0, result_cache=cache)
        base.update(kw)
        return ServingEngine(model, variables, [(T, D)], **base)

    e1 = eng()
    e1.submit(0, [feats_np[0]])
    e1.run_until_idle()
    assert e1.stats()["cache_misses"] == 1

    same = eng()                              # identical config: HIT
    same.submit(0, [feats_np[0]])
    same.run_until_idle()
    assert same.stats()["cache_hits"] == 1

    for other in (eng(beam_size=2),           # beam change
                  eng(decode_chunk=4)):       # tuned-axis change
        other.submit(0, [feats_np[0]])
        other.run_until_idle()
        s = other.stats()
        assert s["cache_hits"] == 0 and s["cache_misses"] == 1

    # A different checkpoint (params fingerprint) must miss too.
    variables2 = make_variables(model, [jnp.asarray(feats_np)],
                                eos_bias=-1.0)
    e2 = ServingEngine(model, variables2, [(T, D)], max_len=MAX_LEN,
                       decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                       result_cache=cache)
    e2.submit(0, [feats_np[0]])
    e2.run_until_idle()
    assert e2.stats()["cache_hits"] == 0 and e2.stats()["cache_misses"] == 1


def test_cache_no_cache_bypass(setup):
    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           result_cache=ResultCache(8))
    engine.submit(0, [feats_np[0]])
    engine.run_until_idle()
    # The miss twin's probe: no_cache skips the lookup AND the write-back
    # consumes nothing — still decodes, still bit-identical.
    engine.submit(1, [feats_np[0]], no_cache=True)
    comps = engine.run_until_idle()
    s = engine.stats()
    assert s["cache_bypass"] == 1 and s["cache_hits"] == 0
    assert not comps[0].cache_hit


def test_shed_request_is_not_a_cache_miss(setup):
    """A shed request never decodes and never writes back, so it must
    not count as a miss — hits+misses stays the number of lookups that
    actually led to a decode (the hit-rate arithmetic)."""
    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=2,
                           result_cache=ResultCache(8))
    results = [engine.submit(i, [feats_np[i]]) for i in range(4)]
    assert results == [True, True, False, False]        # 2 shed
    engine.run_until_idle()
    s = engine.stats()
    assert s["shed"] == 2 and s["cache_misses"] == 2    # not 4
    assert s["cache_entries"] == 2                      # miss == write-back


def test_expired_queued_request_is_not_a_cache_miss(setup):
    """Same invariant on the deadline path: a queued request that
    expires before admission never decodes, so it is no miss either."""
    model, variables, feats_np = setup
    clock = FakeClock()
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0,
                           result_cache=ResultCache(8), clock=clock)
    engine.submit(0, [feats_np[0]], deadline_ms=500)
    clock.tick(1.0)                         # deadline lapsed while queued
    comps = engine.run_until_idle()
    assert not comps
    drops = engine.pop_dropped()
    assert [d.reason for d in drops] == ["expired"]
    s = engine.stats()
    assert s["cache_misses"] == 0 and s["cache_entries"] == 0


def test_dropped_stream_request_gets_terminal_marker(setup):
    """A streamed request that expires still gets ONE terminal line:
    the drop response carries 'stream'/'final' so a client reading
    chunks until the terminal can never hang on an evicted stream."""
    from cst_captioning_tpu.data.vocab import Vocab
    from cst_captioning_tpu.serving.engine import Dropped

    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0)
    out = io.StringIO()
    server = CaptionServer(engine, Vocab({1: "w"}),
                           lambda vid: [feats_np[0]], out=out)
    server._respond_dropped(Dropped(
        ("r", "v0"), "expired", "resident",
        meta={"id": "r", "video_id": "v0", "stream": True}))
    obj = json.loads(out.getvalue())
    assert obj["error"] == "expired"
    assert obj["stream"] is True and obj["final"] is True
    # Non-streamed drops keep the historical shape.
    out.truncate(0), out.seek(0)
    server._respond_dropped(Dropped(
        ("p", "v0"), "expired", "queued",
        meta={"id": "p", "video_id": "v0"}))
    assert "final" not in json.loads(out.getvalue())


def test_shed_and_drain_reject_carry_stream_terminal(setup, monkeypatch):
    """Every streamed request gets exactly ONE terminal line — also on
    the shed and rejected_draining reject paths (SERVING.md)."""
    from cst_captioning_tpu.data.vocab import Vocab

    model, variables, feats_np = setup
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(1,), queue_limit=0)
    out = io.StringIO()
    server = CaptionServer(engine, Vocab({1: "w"}),
                           lambda vid: [feats_np[0]], out=out)
    monkeypatch.setattr(engine, "submit",
                        lambda *a, **k: False)          # force a shed
    server._handle_line_inner(
        json.dumps({"id": 7, "video_id": "v0", "op": "stream"}),
        server._stdout_respond)
    shed = json.loads(out.getvalue())
    assert shed["error"] == "shed"
    assert shed["stream"] is True and shed["final"] is True
    monkeypatch.undo()
    # Drain rejection of a queued streamed request: same terminal.
    engine.submit(8, [feats_np[0]], stream=True,
                  meta={"id": 8, "video_id": "v0", "stream": True})
    out.truncate(0), out.seek(0)
    server.handler = type("H", (), {"requested": True, "signal_count": 0})()
    rc = server._drain_and_exit()
    from cst_captioning_tpu.resilience.exitcodes import EXIT_PREEMPTED

    assert rc == EXIT_PREEMPTED
    lines = [json.loads(l) for l in out.getvalue().splitlines()]
    rej = [r for r in lines if r.get("error") == "rejected_draining"
           and r["id"] == 8]
    assert rej and rej[0]["stream"] is True and rej[0]["final"] is True


def test_feature_fingerprint_exact():
    a = [np.ones((3, 4), np.float32)]
    b = [np.ones((3, 4), np.float32)]
    assert feature_fingerprint(a) == feature_fingerprint(b)
    b[0][0, 0] += 1e-7                        # any bit flip: new key
    assert feature_fingerprint(a) != feature_fingerprint(b)


# -- the serve_cache chaos drill -------------------------------------------


def test_serve_cache_fault_grammar():
    plan = FaultPlan.parse("serve_cache@req=2")
    assert plan.fire("serve_cache", 2)
    assert not plan.fire("serve_cache", 2)     # single-shot
    with pytest.raises(ValueError):
        FaultPlan.parse("serve_cache@step=2")  # wrong axis


def test_serve_cache_chaos_drill_bit_identical(setup):
    """serve_cache@req=N through the recovery plane: the injected lookup
    failure is absorbed — counted, health degraded — and request N's
    caption is bit-identical to the fault-free twin's."""
    model, variables, feats_np = setup
    registry = MetricsRegistry()
    plan = FaultPlan.parse("serve_cache@req=2")
    plan.bind_metrics(registry)        # scripts/serve.py's arming path
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           result_cache=ResultCache(8), fault_plan=plan,
                           recover=True, registry=registry)
    # req 0: decodes video 0 (miss).  req 1: hit.  req 2 (same video):
    # the injected cache failure — must decode fresh, not die, not lose.
    caps = {}
    for rid in (0, 1, 2):
        engine.submit(rid, [feats_np[0]])
        for comp in engine.run_until_idle():
            caps[comp.request_id] = comp
    s = engine.stats()
    assert s["cache_hits"] == 1 and s["cache_errors"] == 1
    np.testing.assert_array_equal(caps[2].tokens, caps[0].tokens)
    np.testing.assert_array_equal(caps[1].tokens, caps[0].tokens)
    assert not caps[2].cache_hit               # decoded fresh
    assert engine.health()["status"] == "degraded"
    snap = registry.snapshot()["counters"]
    assert snap["serve_cache_errors"] == 1
    assert snap["fault_serve_cache"] == 1      # the plan counted its shot


def test_stream_prefix_consistent_across_engine_rebuild(setup):
    """A rebuild's deterministic replay re-derives already-streamed
    tokens but must RE-EMIT none of them (the streamed watermark only
    moves forward): request 0 streams its first chunk, then request 1's
    injected wedge escalates straight to a rebuild (retry_limit=0), and
    after the replay the concatenated chunks still equal the final
    caption bit for bit.  Regression: _caption_so_far once prepended
    res.prefix to the replayed toks, double-counting the pre-rebuild
    tokens."""
    model, variables, feats_np = setup
    offline, _ = sample_captions(model, variables, [jnp.asarray(feats_np)],
                                 jax.random.PRNGKey(0), MAX_LEN, greedy=True)
    plan = FaultPlan.parse("serve_wedge@req=1")
    engine = ServingEngine(model, variables, [(T, D)], max_len=MAX_LEN,
                           decode_chunk=2, bucket_sizes=(2,), queue_limit=0,
                           fault_plan=plan, recover=True, retry_limit=0,
                           rebuild_limit=2)
    # Row 1 runs the full MAX_LEN (the fixture's mild EOS bias only
    # terminates row 0 early) — several chunks stream before the fault.
    engine.submit(0, [feats_np[1]], stream=True)
    comps, chunks = [], []
    comps.extend(engine.step())              # chunk 1: tokens streamed
    chunks.extend(engine.pop_stream_chunks())
    assert chunks, "drill is degenerate: nothing streamed before rebuild"
    engine.submit(1, [feats_np[2]], stream=True)   # wedge fires resident
    while not engine.idle:
        comps.extend(engine.step())
        chunks.extend(engine.pop_stream_chunks())
    s = engine.stats()
    assert s["rebuilds"] == 1 and s["replay_divergence"] == 0
    by_id = {c.request_id: c for c in comps}
    np.testing.assert_array_equal(by_id[0].tokens, np.asarray(offline)[1])
    for rid in (0, 1):
        mine = sorted((c for c in chunks if c.request_id == rid),
                      key=lambda c: c.seq)
        cat = (np.concatenate([c.tokens for c in mine]) if mine
               else np.zeros((0,), np.int32))
        np.testing.assert_array_equal(cat, _trim_eos(by_id[rid].tokens))


# -- the zipfian Poisson probe (make serve-stream-bench's fast twin) -------


def test_zipfian_mix_seeded_and_skewed():
    a = zipfian_mix(64, 4, 1.1, seed=3)
    np.testing.assert_array_equal(a, zipfian_mix(64, 4, 1.1, seed=3))
    counts = np.bincount(a, minlength=4)
    assert counts[0] > counts[3]               # rank 1 dominates rank 4
    np.testing.assert_array_equal(zipfian_mix(6, 3, 0.0),
                                  [0, 1, 2, 0, 1, 2])


def test_probe_stream_cache_zipfian(setup):
    model, variables, _ = setup
    # rate 20/s: ~50ms between arrivals, so each video's miss twin
    # completes (4 tiny chunks) before its first repeat arrives — the
    # hit assertion below cannot race the decode.
    out = serving_probe(model, variables, [(T, D)],
                        num_requests=10, rate_hz=20.0, max_len=MAX_LEN,
                        decode_chunk=2, bucket_sizes=(1, 2), seed=4,
                        stream=True, cache_size=8, unique_videos=3,
                        zipf_alpha=1.1)
    assert out["completed"] == 10 and out["shed"] == 0
    assert out["recompiles_after_warmup"] == 0
    assert out["unique_videos"] == 3 and out["zipf_alpha"] == 1.1
    st = out["stream"]
    assert st["enabled"] and st["prefix_ok"] and st["chunks"] >= 1
    assert st["ttft_p50_ms"] is not None
    ca = out["cache"]
    assert ca["enabled"] and ca["parity_ok"]
    assert ca["hits"] >= 1                     # repeats hit after the twin
    assert ca["hits"] + ca["misses"] == 10
    assert ca["hit_rate"] == pytest.approx(ca["hits"] / 10)


def test_probe_defaults_unchanged(setup):
    """The historical probe surface (no stream, no cache, unique-per-
    request mix) still reports the same fields with the floors off."""
    model, variables, _ = setup
    out = serving_probe(model, variables, [(T, D)],
                        num_requests=6, rate_hz=50.0, max_len=MAX_LEN,
                        decode_chunk=2, bucket_sizes=(1, 2), seed=4)
    assert out["completed"] == 6
    assert out["stream"] == {"enabled": False}
    assert out["cache"] == {"enabled": False}
    assert out["unique_videos"] == 6


# -- serve_report: rows + the two new gates --------------------------------


BASE_RECORD = {
    "metric": "serve_captions_per_sec_per_chip", "value": 50.0,
    "latency_p50_ms": 1.0, "latency_p99_ms": 2.0,
    "completed": 8, "num_requests": 8, "shed": 0,
    "recompiles_after_warmup": 0, "rebuild_recompiles": 0,
    "platform": "cpu",
    "stream": {"enabled": True, "chunks": 12, "ttft_p50_ms": 0.5,
               "ttft_p99_ms": 1.5, "chunk_gap_p50_ms": 0.3,
               "chunk_gap_p99_ms": 0.9, "prefix_ok": True},
    "cache": {"enabled": True, "hits": 5, "misses": 3, "evictions": 0,
              "bypass": 0, "errors": 0, "hit_rate": 0.625,
              "parity_ok": True, "parity_mismatches": 0},
    "cache_off_captions_per_sec": 30.0, "cache_speedup": 1.667,
}


def _run_report(record, tmp_path):
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(record) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)


def test_serve_report_renders_stream_and_cache_rows(tmp_path):
    proc = _run_report(BASE_RECORD, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "ttft p50 / p99" in proc.stdout
    assert "inter-chunk gap" in proc.stdout
    assert "62.5%" in proc.stdout              # cache hit rate
    assert "cache-off twin" in proc.stdout
    assert "parity_ok=True" in proc.stdout


def test_serve_report_gates_on_cache_parity(tmp_path):
    bad = {**BASE_RECORD,
           "cache": {**BASE_RECORD["cache"], "parity_ok": False,
                     "parity_mismatches": 2}}
    proc = _run_report(bad, tmp_path)
    assert proc.returncode == 1
    assert "not bit-identical to their miss twin" in proc.stderr


def test_serve_report_gates_on_cache_not_paying(tmp_path):
    bad = {**BASE_RECORD, "cache_off_captions_per_sec": 60.0}
    proc = _run_report(bad, tmp_path)
    assert proc.returncode == 1
    assert "did not beat its cache-off twin" in proc.stderr


def test_serve_report_old_records_still_render(tmp_path):
    """Pre-ISSUE-12 records (no stream/cache sections) keep working."""
    old = {k: v for k, v in BASE_RECORD.items()
           if k not in ("stream", "cache", "cache_off_captions_per_sec",
                        "cache_speedup")}
    proc = _run_report(old, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "ttft" not in proc.stdout


# -- opts ------------------------------------------------------------------


def test_serve_cache_flag_validation():
    from cst_captioning_tpu.opts import parse_opts

    assert parse_opts([]).serve_cache == 256   # shipped default: armed
    assert parse_opts(["--serve_cache", "0"]).serve_cache == 0
    with pytest.raises(SystemExit) as exc:
        parse_opts(["--serve_cache", "-3"])
    assert exc.value.code == 2
