import json

import pytest

from cst_captioning_tpu.metrics.coco_eval import language_eval, load_cocofmt_refs


REFS = {
    "vid1": ["A man is cooking food.", "a man cooks in a kitchen"],
    "vid2": ["A dog runs in the park.", "the dog is running outside"],
}


def test_scores_all_metrics():
    preds = [
        {"image_id": "vid1", "caption": "a man is cooking food"},
        {"image_id": "vid2", "caption": "a dog runs in the park"},
    ]
    out = language_eval(preds, REFS)
    for key in ("Bleu_1", "Bleu_4", "METEOR_approx", "ROUGE_L", "CIDEr"):
        assert key in out
    # the approximated metric must NEVER appear under the bare jar name
    assert "METEOR" not in out
    # Predictions match one reference each (mod tokenization) → near-perfect B1/ROUGE.
    assert out["Bleu_1"] > 0.95
    assert out["ROUGE_L"] > 0.95
    assert out["CIDEr"] > 0.5


def test_tokenization_normalizes_case_and_punct():
    exact = [{"image_id": "vid1", "caption": "A man is cooking food."}]
    noisy = [{"image_id": "vid1", "caption": "a man is cooking food"}]
    assert language_eval(exact, REFS) == language_eval(noisy, REFS)


def test_cocofmt_file_roundtrip(tmp_path):
    coco = {
        "annotations": [
            {"image_id": "vid1", "caption": c} for c in REFS["vid1"]
        ] + [
            {"image_id": "vid2", "caption": c} for c in REFS["vid2"]
        ],
        "images": [{"id": "vid1"}, {"id": "vid2"}],
    }
    p = tmp_path / "refs_cocofmt.json"
    p.write_text(json.dumps(coco))
    refs = load_cocofmt_refs(str(p))
    assert set(refs) == {"vid1", "vid2"}
    preds = [{"image_id": "vid1", "caption": "a man is cooking food"},
             {"image_id": "vid2", "caption": "a dog runs"}]
    out = language_eval(preds, str(p))
    assert out["Bleu_1"] > 0.5


def test_missing_reference_raises():
    with pytest.raises(KeyError):
        language_eval([{"image_id": "nope", "caption": "x"}], REFS)
