"""Fleet serving (ISSUE 13): health-aware router over N self-healing
engine replicas.

Fast slice (tier-1, lock-sanitizer armed like the PR 9/11 slices):
- the ``@replica=K`` fault-plan axis (grammar, rejection, per-replica
  derivation firing once at any index);
- routing: load spread with ``fleet_routed``/``fleet_rerouted``
  accounting, route-around-``degraded``, fleet-edge deadline shed with
  ``where: fleet`` at the router AND on the server wire;
- THE fleet acceptance drill: replica-targeted faults + a hard replica
  kill mid-flight — every request answered, captions BIT-IDENTICAL to a
  fault-free single-engine run, zero program builds after warmup
  including through the replica restart (shared ProgramCache);
- lifecycle: the in-process exit-124 (``ServingUnrecoverable``) consumed
  as "restart replica, re-queue residents"; the restart budget
  escalating to ``FleetUnrecoverable``; draining rotation admitting
  nothing to the rotating replica and rebuilding it warm;
- one shared result cache across replicas; streamed requests staying
  prefix-consistent across a replica kill (fleet watermarks);
- the fleet health view (worst-of-replicas + per-replica detail)
  through the server's pluggable health source;
- serve_report's fleet rows + bit-identity gate; bench cache identity
  carrying ``replicas``; doc pins (SERVING.md fleet counter table,
  RESILIENCE.md ``@replica=K`` grammar row).

The subprocess drill (scripts/serve_fleet.py under a real ``@replica``
fault plan) is marked ``slow`` and runs via ``make serve-fleet-chaos``.
"""

import argparse
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.resilience.faults import ANY_INDEX, FaultPlan
from cst_captioning_tpu.serving.buckets import ProgramCache
from cst_captioning_tpu.serving.cache import ResultCache
from cst_captioning_tpu.serving.engine import ServingEngine, _trim_eos
from cst_captioning_tpu.serving.fleet import (
    FLEET_COUNTERS,
    FleetRouter,
    FleetUnrecoverable,
)
from cst_captioning_tpu.serving.server import CaptionServer
from cst_captioning_tpu.telemetry.registry import MetricsRegistry

V, B, T, D, MAX_LEN = 12, 5, 3, 7, 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _lock_sanitizer(monkeypatch, tmp_path):
    """The fleet fast slice runs sanitizer-armed (the PR 11 discipline):
    router + engine + registry locks are re-validated against the
    declared LOCK_ORDER under every drill in this file."""
    from cst_captioning_tpu.analysis import locksan

    receipt = tmp_path / "locksan_violation.json"
    monkeypatch.setenv(locksan.ENV_FLAG, "1")
    monkeypatch.setenv(locksan.ENV_RECEIPT, str(receipt))
    before = len(locksan.violations())
    yield
    after = locksan.violations()
    assert len(after) == before, f"lock-order violations: {after[before:]}"
    assert not receipt.exists(), (
        f"lock sanitizer receipt from a child process: "
        f"{receipt.read_text()}")


def make_variables(model, feats, eos_bias=0.4):
    variables = model.init(jax.random.PRNGKey(0), feats,
                           np.zeros((B, MAX_LEN), np.int32))
    params = {**variables["params"]}
    params["logit"] = {**params["logit"]}
    params["logit"]["bias"] = params["logit"]["bias"].at[0].add(eos_bias)
    return {"params": params}


@pytest.fixture(scope="module")
def setup():
    """EOS-suppressed model (captions run the full MAX_LEN) so residents
    stay in flight across the kill/rotation windows deterministically."""
    model = CaptionModel(vocab_size=V, embed_size=16, hidden_size=16,
                         attn_size=16, dropout_rate=0.0)
    feats_np = np.random.default_rng(0).normal(
        size=(B, T, D)).astype(np.float32) * 2.0
    variables = make_variables(model, [jnp.asarray(feats_np)],
                               eos_bias=-2.0)
    return model, variables, feats_np


def build_fleet(setup, replicas=2, *, registry=None, plan=None,
                result_cache=None, recover=True, retry_limit=2,
                rebuild_limit=2, restart_limit=3, deadline_ms=0.0,
                queue_limit=0, clock=None, lifecycle=None):
    """A fleet over shared ProgramCache (+ optional shared result
    cache); returns (fleet, programs, factory) — the factory doubles as
    the fault-free single-engine reference builder."""
    model, variables, _ = setup
    programs = ProgramCache(registry)

    def factory(k, _plan=None):
        use = plan.for_replica(k) if (plan is not None and _plan is None) \
            else _plan
        kw = {}
        if clock is not None:
            kw["clock"] = clock
        if lifecycle is not None:
            kw["lifecycle"] = lifecycle.for_replica(k)
        return ServingEngine(
            model, variables, [(T, D)], max_len=MAX_LEN, decode_chunk=2,
            bucket_sizes=(1, 2), queue_limit=queue_limit,
            deadline_ms=deadline_ms, fault_plan=use, recover=recover,
            retry_limit=retry_limit, rebuild_limit=rebuild_limit,
            result_cache=result_cache, program_cache=programs,
            registry=registry, **kw)

    fleet_kw = {}
    if clock is not None:
        fleet_kw["clock"] = clock
    if lifecycle is not None:
        fleet_kw["lifecycle"] = lifecycle
    fleet = FleetRouter(factory, replicas, restart_limit=restart_limit,
                        registry=registry, **fleet_kw)
    return fleet, programs, factory


def reference_tokens(factory, vids):
    """Fault-free single-engine decode of every video — the fleet
    acceptance baseline (plan-free, cache-free by construction: the
    factory's _plan override pins None)."""
    eng = factory(0, _plan=None)
    for i, f in enumerate(vids):
        eng.submit(("ref", i), f)
    return {c.request_id[1]: np.asarray(c.tokens)
            for c in eng.run_until_idle()}


def make_videos(n, seed=1):
    rng = np.random.default_rng(seed)
    return [[rng.standard_normal((T, D)).astype(np.float32)]
            for _ in range(n)]


# -- @replica=K fault axis -------------------------------------------------


def test_replica_axis_parses_and_derives():
    plan = FaultPlan.parse("serve_wedge@replica=1,serve_garble@req=2")
    assert "serve_wedge@replica=1" in str(plan)
    # The parsed plan never fires a replica spec itself (@req specs
    # still work); only the per-replica derivative does.
    assert not plan.fire("serve_wedge", 0)
    assert plan.fire("serve_garble", 2)
    d1 = plan.for_replica(1)
    assert d1 is not None and d1.specs[0].at == ANY_INDEX
    # Fires at the FIRST probed index, once — any index, single shot.
    assert d1.fire("serve_wedge", 7)
    assert not d1.fire("serve_wedge", 8)
    # Untargeted replicas pay nothing: no derived plan at all.
    assert plan.for_replica(0) is None


def test_replica_axis_rejects_bad_specs():
    with pytest.raises(ValueError, match="cannot target a fleet replica"):
        FaultPlan.parse("nan_grad@replica=0")
    with pytest.raises(ValueError, match="no \\*K repeat"):
        FaultPlan.parse("serve_wedge@replica=0*2")
    # And the CLI surfaces it as a one-line usage error.
    from cst_captioning_tpu.opts import parse_opts

    with pytest.raises(SystemExit) as exc:
        parse_opts(["--fault_plan", "wedge@replica=1"])
    assert exc.value.code == 2
    ns = parse_opts(["--fault_plan", "admit_err@replica=2"])
    assert ns.fault_plan == "admit_err@replica=2"


# -- routing ---------------------------------------------------------------


def test_fleet_spreads_load_and_counts(setup):
    registry = MetricsRegistry()
    fleet, _, _ = build_fleet(setup, 2, registry=registry)
    fleet.warm()
    vids = make_videos(6)
    done = []
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done += fleet.run_until_idle()
    assert sorted(c.request_id for c in done) == list(range(6))
    st = fleet.stats()
    assert st["fleet"]["fleet_routed"] == 6
    assert registry.counter("fleet_routed") == 6
    # Least-loaded routing put work on BOTH replicas.
    per = st["per_replica"]
    assert len(per) == 2 and all(p["completed"] > 0 for p in per)
    # Declared at 0: every fleet counter exists even where nothing fired.
    snap = registry.snapshot()["counters"]
    for name in FLEET_COUNTERS:
        assert name in snap, name


def test_fleet_captions_bit_identical_to_single_engine(setup):
    fleet, _, factory = build_fleet(setup, 3)
    fleet.warm()
    vids = make_videos(6, seed=5)
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    got = {c.request_id: np.asarray(c.tokens)
           for c in fleet.run_until_idle()}
    ref = reference_tokens(factory, vids)
    assert sorted(got) == list(range(6))
    for i in range(6):
        np.testing.assert_array_equal(got[i], ref[i])


def test_route_around_degraded(setup):
    fleet, _, _ = build_fleet(setup, 2)
    fleet.warm()
    # Replica 0 just recovered from something: health 'degraded'.
    fleet._replicas[0].engine._note_recovery_event()
    for i, f in enumerate(make_videos(3, seed=2)):
        assert fleet.submit(i, f)
    # Everything routed AROUND the degraded replica.
    assert fleet._replicas[0].engine.queue_depth == 0
    assert fleet._replicas[0].engine.resident_count == 0
    assert fleet._replicas[1].engine.queue_depth + \
        fleet._replicas[1].engine.resident_count == 3
    fleet._update_snapshots()      # health() is snapshot-backed
    assert fleet.health()["per_replica"][0]["status"] == "degraded"
    done = fleet.run_until_idle()
    assert len(done) == 3


def test_fleet_edge_shed_where_fleet(setup):
    registry = MetricsRegistry()
    fleet, _, _ = build_fleet(setup, 2, registry=registry,
                              deadline_ms=1.0)
    fleet.warm()
    # Every replica's p99 chunk floor is known and far above 1ms.
    for rep in fleet._replicas:
        rep.engine._chunk_wall.extend([0.05] * 8)
    assert fleet.submit("r1", make_videos(1)[0], deadline_ms=1.0)
    drops = fleet.pop_dropped()
    assert len(drops) == 1
    assert drops[0].reason == "deadline_shed" and drops[0].where == "fleet"
    assert registry.counter("fleet_shed") == 1
    # Nothing ever queued at a replica.
    assert all(r.engine.queue_depth == 0 for r in fleet._replicas)
    # An unknown floor at any replica = not provable = admit normally.
    fleet._replicas[0].engine._chunk_wall.clear()
    assert fleet.submit("r2", make_videos(1)[0], deadline_ms=1.0)
    assert not fleet.pop_dropped()


def test_server_renders_fleet_shed_where_fleet(setup):
    from cst_captioning_tpu.serving.engine import Dropped

    fleet, _, _ = build_fleet(setup, 2)
    fleet.warm()
    out = []
    server = CaptionServer(fleet, vocab=None, feats_for=lambda v: None)
    server._respond_dropped(Dropped("x", "deadline_shed", "fleet",
                                    meta={"id": 9, "video_id": "v",
                                          "respond": out.append}))
    obj = json.loads(out[0])
    assert obj["error"] == "expired" and obj["where"] == "fleet"
    assert obj["why"] == "deadline_unmeetable"


# -- lifecycle: kill / 124 / budget / rotation -----------------------------


def test_kill_replica_requeues_bit_identical_zero_compiles(setup):
    registry = MetricsRegistry()
    fleet, programs, factory = build_fleet(setup, 2, registry=registry)
    warm = fleet.warm()["compiles"]
    vids = make_videos(6, seed=3)
    done = []
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done += fleet.step()          # residents mid-flight on both replicas
    assert fleet._replicas[0].engine.resident_count > 0
    fleet.kill_replica(0)
    done += fleet.run_until_idle()
    # Every request answered with a caption (none dropped), captions
    # bit-identical to the fault-free single-engine run.
    got = {c.request_id: np.asarray(c.tokens) for c in done}
    assert sorted(got) == list(range(6))
    assert fleet.pop_dropped() == []
    ref = reference_tokens(factory, vids)
    for i in range(6):
        np.testing.assert_array_equal(got[i], ref[i])
    # Zero builds through the kill/restart: the restarted replica
    # re-warmed entirely from the shared ProgramCache.
    assert programs.builds == warm
    st = fleet.stats()["fleet"]
    assert st["fleet_replica_kills"] == 1
    assert st["fleet_replica_restarts"] == 1
    assert st["fleet_rerouted"] >= 1
    assert registry.counter("fleet_replica_kills") == 1


def test_unrecoverable_replica_consumed_as_supervised_restart(setup):
    """The exit-124 taxonomy one level down: a replica whose self-healing
    ladder exhausts (ServingUnrecoverable) is restarted by the router
    with its residents re-queued — the fleet answer to what a process
    supervisor does with exit 124."""
    plan = FaultPlan.parse("serve_wedge@replica=0")
    fleet, programs, factory = build_fleet(
        setup, 2, plan=plan, retry_limit=0, rebuild_limit=0)
    warm = fleet.warm()["compiles"]
    vids = make_videos(4, seed=4)
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done = fleet.run_until_idle()
    got = {c.request_id: np.asarray(c.tokens) for c in done}
    assert sorted(got) == list(range(4))
    ref = reference_tokens(factory, vids)
    for i in range(4):
        np.testing.assert_array_equal(got[i], ref[i])
    st = fleet.stats()["fleet"]
    assert st["fleet_replica_restarts"] == 1
    assert st["fleet_replica_kills"] == 0      # a 124, not a drill kill
    assert programs.builds == warm


def test_restart_budget_escalates_to_fleet_unrecoverable(setup):
    fleet, _, _ = build_fleet(setup, 2, restart_limit=0)
    fleet.warm()
    vids = make_videos(2, seed=6)
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    fleet.step()
    fleet.kill_replica(0)          # budget 0: replica 0 is now dead
    assert fleet.health()["per_replica"][0]["status"] == "dead"
    # The fleet view degrades (capacity lost) but keeps serving.
    assert fleet.health()["status"] == "degraded"
    with pytest.raises(FleetUnrecoverable):
        fleet.kill_replica(1)      # last replica out -> process-level 124
    # Still no silent loss: the evacuated requests were ANSWERED.
    drops = fleet.pop_dropped()
    assert {d.request_id for d in drops} <= {0, 1}
    assert all(d.reason == "admit_failed" and d.where == "fleet"
               for d in drops)
    # Review regression: budget-exhausted removals are NOT restarts —
    # both kills went straight to dead, nothing was rebuilt.
    assert fleet.fleet_counters()["fleet_replica_restarts"] == 0
    assert fleet.fleet_counters()["fleet_replica_kills"] == 2


def test_death_mid_rotation_clears_draining_and_escalates(setup):
    """Review regression: a replica that dies past its budget WHILE
    draining must drop the draining flag — otherwise the zombie flag
    blocks FleetUnrecoverable forever (submit sheds instead of exiting
    124) and ``idle`` never settles."""
    fleet, _, _ = build_fleet(setup, 1, restart_limit=0)
    fleet.warm()
    assert fleet.submit(0, make_videos(1, seed=16)[0])
    fleet.step()
    fleet.rotate(0)                # the only replica is draining...
    with pytest.raises(FleetUnrecoverable):
        fleet.kill_replica(0)      # ...and dies mid-rotation
    assert not fleet._replicas[0].draining
    drops = fleet.pop_dropped()    # the resident was still answered
    assert [d.request_id for d in drops] == [0]
    assert fleet.idle              # no zombie draining flag


def test_rotation_admits_nothing_and_rebuilds_warm(setup):
    registry = MetricsRegistry()
    fleet, programs, _ = build_fleet(setup, 2, registry=registry)
    warm = fleet.warm()["compiles"]
    vids = make_videos(4, seed=7)
    for i, f in enumerate(vids[:2]):
        assert fleet.submit(i, f)
    fleet.step()                    # residents on both replicas
    fleet.rotate(0)
    assert fleet.health()["per_replica"][0]["status"] == "draining"
    # Worst-of-replicas: a rotating replica shows in the fleet status.
    assert fleet.health()["status"] == "draining"
    # New traffic admits NOTHING to the rotating replica.
    before = fleet._replicas[0].engine.queue_depth
    for i, f in enumerate(vids[2:], start=2):
        assert fleet.submit(i, f)
    assert fleet._replicas[0].engine.queue_depth == before == 0
    done = fleet.run_until_idle()
    assert sorted(c.request_id for c in done) == list(range(4))
    # Rotation finished: rebuilt warm (zero builds), back in service.
    assert fleet.health()["per_replica"][0]["status"] == "ok"
    assert fleet._replicas[0].in_service
    assert programs.builds == warm
    assert registry.counter("fleet_replica_restarts") == 1


def test_replica_targeted_fault_hits_only_that_replica(setup):
    plan = FaultPlan.parse("serve_garble@replica=1")
    registry = MetricsRegistry()
    fleet, _, factory = build_fleet(setup, 2, plan=plan,
                                    registry=registry)
    fleet.warm()
    vids = make_videos(4, seed=8)
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done = fleet.run_until_idle()
    assert len(done) == 4
    rec0 = fleet._replicas[0].engine.recovery_counters()
    rec1 = fleet._replicas[1].engine.recovery_counters()
    assert rec0["garble_detected"] == 0
    assert rec1["garble_detected"] == 1 and rec1["chunk_retries"] >= 1
    ref = reference_tokens(factory, vids)
    for c in done:
        np.testing.assert_array_equal(np.asarray(c.tokens),
                                      ref[c.request_id])


def test_fleet_acceptance_drill_all_faults_plus_kill(setup):
    """THE fleet acceptance drill (ISSUE 13): seeded serve_wedge /
    serve_garble / admit_err fired at individual replicas plus one hard
    replica kill/restart — every request answered, captions
    bit-identical to the fault-free single-engine run, zero post-warmup
    compiles fleet-wide including through the restart, every fault
    visible in the counters."""
    plan = FaultPlan.parse(
        "serve_wedge@replica=0,serve_garble@replica=1,admit_err@replica=0")
    registry = MetricsRegistry()
    plan.bind_metrics(registry)
    fleet, programs, factory = build_fleet(setup, 3, plan=plan,
                                           registry=registry)
    warm = fleet.warm()["compiles"]
    vids = make_videos(9, seed=12)
    done = []
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done += fleet.step()
    fleet.kill_replica(2)
    done += fleet.run_until_idle()
    got = {c.request_id: np.asarray(c.tokens) for c in done}
    assert sorted(got) == list(range(9))      # every request answered
    assert fleet.pop_dropped() == []
    ref = reference_tokens(factory, vids)
    for i in range(9):
        np.testing.assert_array_equal(got[i], ref[i])
    assert programs.builds == warm            # zero compiles fleet-wide
    rec = fleet.recovery_counters()
    # Each targeted fault fired exactly once and was absorbed in place
    # (rec sums LIVE engines; the killed replica 2 carried no faults).
    assert registry.counter("fault_serve_wedge") == 1
    assert registry.counter("fault_serve_garble") == 1
    assert registry.counter("fault_admit_err") == 1
    assert rec["wedge_detected"] == 1
    assert rec["garble_detected"] == 1
    assert rec["admit_errors"] == 1
    st = fleet.stats()["fleet"]
    assert st["fleet_replica_kills"] == 1
    assert st["fleet_replica_restarts"] == 1


# -- shared result cache / streaming continuity ----------------------------


def test_shared_result_cache_across_replicas(setup):
    registry = MetricsRegistry()
    cache = ResultCache(16)
    fleet, _, _ = build_fleet(setup, 2, registry=registry,
                              result_cache=cache)
    fleet.warm()
    vid = make_videos(1, seed=9)[0]
    assert fleet.submit("a", vid)
    first = fleet.run_until_idle()
    assert len(first) == 1 and not first[0].cache_hit
    # The same video again: wherever it routes, the shared cache hits —
    # one decode per distinct video FLEET-wide.
    assert fleet.submit("b", vid)
    second = fleet.run_until_idle()
    assert len(second) == 1 and second[0].cache_hit
    np.testing.assert_array_equal(np.asarray(second[0].tokens),
                                  np.asarray(first[0].tokens))
    cc = fleet.stats()
    assert cc["cache_hits"] == 1 and cc["cache_misses"] == 1
    assert cc["cache_entries"] == 1


def test_stream_prefix_consistent_across_replica_kill(setup):
    """The fleet watermark: a killed replica's streamed request replays
    from step 0 on its new owner; the client still sees each token
    exactly once and the concatenation equals the final caption."""
    fleet, _, _ = build_fleet(setup, 2)
    fleet.warm()
    vids = make_videos(2, seed=10)
    chunks = {0: [], 1: []}
    done = []
    for i, f in enumerate(vids):
        assert fleet.submit(i, f, stream=True)
    done += fleet.step()            # first chunks emitted
    for ch in fleet.pop_stream_chunks():
        chunks[ch.request_id].append(ch)
    assert any(chunks.values())
    fleet.kill_replica(0)
    while not fleet.idle:
        done += fleet.step()
        for ch in fleet.pop_stream_chunks():
            chunks[ch.request_id].append(ch)
    assert sorted(c.request_id for c in done) == [0, 1]
    for c in done:
        got = (np.concatenate([np.asarray(x.tokens) for x in
                               sorted(chunks[c.request_id],
                                      key=lambda x: x.seq)])
               if chunks[c.request_id] else np.zeros((0,), np.int32))
        np.testing.assert_array_equal(got, _trim_eos(c.tokens))
        # Fleet-side re-sequencing: seq is gapless from 0.
        seqs = [x.seq for x in sorted(chunks[c.request_id],
                                      key=lambda x: x.seq)]
        assert seqs == list(range(len(seqs)))


def test_requeue_preserves_no_cache(setup):
    """Review regression: an evacuated no_cache request must stay
    no_cache on its new engine — the per-request bypass survives a
    replica kill instead of silently hitting the shared cache."""
    cache = ResultCache(16)
    fleet, _, _ = build_fleet(setup, 2, result_cache=cache)
    fleet.warm()
    vid = make_videos(1, seed=13)[0]
    # Prime the shared cache with this video's caption.
    assert fleet.submit("prime", vid)
    assert fleet.run_until_idle()[0].cache_hit is False
    # A no_cache twin, evacuated mid-flight by a replica kill.
    assert fleet.submit("bypass", vid, no_cache=True)
    owner = next(r.index for r in fleet._replicas
                 if r.engine.queue_depth + r.engine.resident_count)
    fleet.step()
    fleet.kill_replica(owner)
    done = fleet.run_until_idle()
    comp = next(c for c in done if c.request_id == "bypass")
    assert comp.cache_hit is False           # decoded fresh, post-requeue
    assert comp.decode_steps > 0
    # The requeued submission bypassed again on its NEW engine (stats
    # sum live engines; the killed engine's count retired with it).
    assert fleet.stats()["cache_bypass"] >= 1


def test_dropped_stream_watermark_forgotten_and_id_reuse(setup):
    """Review regression: a dropped streamed request releases its fleet
    watermark, and a REUSED request id streams from scratch instead of
    being filtered against the stale state."""
    clock_t = [0.0]
    clock = lambda: clock_t[0]  # noqa: E731
    fleet, _, _ = build_fleet(setup, 2, deadline_ms=0.0, clock=clock)
    fleet.warm()
    vid = make_videos(1, seed=14)[0]
    assert fleet.submit("rid", vid, stream=True)
    fleet.step()
    first = fleet.pop_stream_chunks()
    assert first and first[0].request_id == "rid"
    # Expire it mid-flight: terminal drop, watermark must be released.
    clock_t[0] = 10.0
    fleet._replicas[0].engine.deadline_ms = 0.0
    for rep in fleet._replicas:
        for res in rep.engine._residents:
            if res is not None:
                res.request.deadline = 5.0
    fleet.step()
    drops = fleet.pop_dropped()
    assert [d.request_id for d in drops] == ["rid"]
    assert "rid" not in fleet._stream_sent
    # The reused id streams its FULL caption (nothing filtered).
    assert fleet.submit("rid", vid, stream=True)
    chunks = []
    done = []
    while not fleet.idle:
        done += fleet.step()
        chunks += fleet.pop_stream_chunks()
    comp = next(c for c in done if c.request_id == "rid")
    got = np.concatenate([np.asarray(c.tokens)
                          for c in sorted(chunks, key=lambda c: c.seq)])
    np.testing.assert_array_equal(got, _trim_eos(comp.tokens))


def test_submit_during_last_replica_rotation_sheds_not_124(setup):
    """Review regression: with every routable replica mid-rotation the
    fleet SHEDS (client retry signal) instead of raising
    FleetUnrecoverable — the rotation finishes and service resumes."""
    fleet, _, _ = build_fleet(setup, 1)
    fleet.warm()
    vids = make_videos(2, seed=15)
    assert fleet.submit(0, vids[0])
    fleet.step()
    fleet.rotate(0)                    # the only replica is now draining
    assert fleet.submit(1, vids[1]) is False      # shed, not a raise
    assert fleet.stats()["fleet"]["fleet_shed"] == 1
    done = fleet.run_until_idle()      # rotation completes
    assert [c.request_id for c in done] == [0]
    assert fleet._replicas[0].in_service
    assert fleet.submit(1, vids[1])    # service resumed
    assert len(fleet.run_until_idle()) == 1


# -- the fleet health plane through the server -----------------------------


def test_server_health_source_renders_fleet_view(setup):
    fleet, _, _ = build_fleet(setup, 2)
    fleet.warm()
    server = CaptionServer(fleet, vocab=None, feats_for=lambda v: None,
                           health_source=fleet.health)
    h = server.health_payload()
    assert h["op"] == "health" and h["status"] == "ok"
    assert h["replicas"] == 2 and len(h["per_replica"]) == 2
    assert set(h["fleet"]) == set(FLEET_COUNTERS)
    # Worst-of-replicas flows through the pluggable source...
    fleet._replicas[1].engine._note_recovery_event()
    fleet._update_snapshots()
    assert server.health_payload()["status"] == "degraded"
    # ...and the server's own draining state still dominates.
    server._draining = True
    assert server.health_payload()["status"] == "draining"


# -- bench probe / cache identity / serve_report ---------------------------


def test_fleet_probe_parity_and_recompile_contract(setup):
    from cst_captioning_tpu.serving.bench import serving_probe

    model, variables, _ = setup
    out = serving_probe(model, variables, [(T, D)], num_requests=8,
                        rate_hz=500.0, max_len=MAX_LEN, decode_chunk=2,
                        bucket_sizes=(1, 2), queue_limit=0, seed=11,
                        replicas=2, kill_replica=0)
    fleet = out["fleet"]
    assert fleet["enabled"] and fleet["replicas"] == 2
    assert fleet["killed_replica"] == 0
    assert fleet["fleet_replica_kills"] == 1
    assert fleet["parity_ok"] is True and fleet["parity_mismatches"] == 0
    assert fleet["answered"] == 8 and out["completed"] == 8
    assert out["recompiles_after_warmup"] == 0
    assert len(fleet["per_replica"]) == 2
    assert out["captions_per_sec"] > 0


def test_bench_cache_identity_includes_fleet_axes():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)

    base = dict(batch_size=2, seq_per_img=2, seq_len=8, vocab=60,
                hidden=16, bfloat16=0, native_cider=0, decode_chunk=2,
                scan_unroll=1, decode_kernel="reference", overlap_depth=1,
                device_rewards=1, stage="serving", serve_requests=8,
                serve_rate=6.0, serve_buckets="1,4", serve_beam=1,
                serve_stream=0, serve_cache=0, serve_zipf=0.0,
                serve_unique=None, serve_cache_compare=0)
    one = bench.resolved_config(argparse.Namespace(
        **base, replicas=1, serve_kill_replica=-1))
    three = bench.resolved_config(argparse.Namespace(
        **base, replicas=3, serve_kill_replica=1))
    assert one["replicas"] == 1 and three["replicas"] == 3
    assert one != three    # fleet and single-engine records never collide


def _run_report(record, tmp_path):
    path = tmp_path / "serving.json"
    path.write_text(json.dumps(record) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_report.py"),
         "--file", str(path)], capture_output=True, text=True, cwd=REPO)


def _fleet_record(**over):
    rec = {
        "metric": "serve_captions_per_sec_per_chip", "value": 100.0,
        "latency_p50_ms": 5.0, "latency_p99_ms": 9.0, "completed": 8,
        "num_requests": 8, "shed": 0, "recompiles_after_warmup": 0,
        "rebuild_recompiles": 0, "platform": "cpu",
        "fleet": {"enabled": True, "replicas": 2, "fleet_routed": 8,
                  "fleet_rerouted": 1, "fleet_shed": 0,
                  "fleet_replica_restarts": 1, "fleet_replica_kills": 1,
                  "killed_replica": 0, "parity_ok": True,
                  "parity_mismatches": 0,
                  "per_replica": [
                      {"replica": 0, "status": "ok", "completed": 4,
                       "restarts": 1, "kills": 1},
                      {"replica": 1, "status": "ok", "completed": 4,
                       "restarts": 0, "kills": 0}]},
    }
    rec["fleet"].update(over)
    return rec


def test_serve_report_renders_fleet_rows(tmp_path):
    proc = _run_report(_fleet_record(), tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "captions/s/fleet" in proc.stdout
    assert "replica 0" in proc.stdout and "replica 1" in proc.stdout
    assert "parity_ok=True" in proc.stdout


def test_serve_report_gates_on_fleet_parity(tmp_path):
    proc = _run_report(_fleet_record(parity_ok=False,
                                     parity_mismatches=2), tmp_path)
    assert proc.returncode == 1
    assert "bit-identical" in proc.stderr


def test_serve_report_old_records_render_unchanged(tmp_path):
    rec = {"metric": "serve_captions_per_sec_per_chip", "value": 50.0,
           "latency_p50_ms": 4.0, "latency_p99_ms": 8.0,
           "recompiles_after_warmup": 0, "platform": "cpu"}
    proc = _run_report(rec, tmp_path)
    assert proc.returncode == 0, proc.stderr
    assert "captions/s " in proc.stdout or "captions/s\n" in proc.stdout
    assert "fleet" not in proc.stdout


# -- doc pins --------------------------------------------------------------


def test_serving_doc_pins_fleet_counter_table():
    with open(os.path.join(REPO, "SERVING.md")) as f:
        text = f.read()
    for name in FLEET_COUNTERS:
        assert name in text, f"SERVING.md fleet table missing {name}"
    for token in ("worst-of-replicas", "rotate", "serve_fleet.py",
                  "--replicas", "serve-fleet-chaos"):
        assert token in text, f"SERVING.md Fleet section missing {token!r}"


def test_resilience_doc_pins_replica_axis():
    with open(os.path.join(REPO, "RESILIENCE.md")) as f:
        text = f.read()
    assert "kind@replica=K" in text
    assert "for_replica" in text


# -- request lifecycle across the fleet (ISSUE 14) -------------------------


def test_kill_requeue_lifecycle_trace_and_attribution(setup):
    """The ISSUE-14 satellite drill: a hard replica kill mid-request —
    the lifecycle stream shows killed -> requeued -> completed in
    order, the requeue window is attributed (recovery time visible,
    never hidden), every id reaches exactly one terminal, and captions
    stay bit-identical to the fault-free single-engine run."""
    from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer

    lc = LifecycleTracer()
    fleet, programs, factory = build_fleet(setup, 2, lifecycle=lc)
    fleet.warm()
    vids = make_videos(6, seed=3)
    done = []
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done += fleet.step()
    eng0 = fleet._replicas[0].engine
    assert eng0.resident_count > 0
    killed_ids = [req.request_id for req in eng0.resident_requests()]
    fleet.kill_replica(0)
    done += fleet.run_until_idle()
    # Accounting/attribution BEFORE the untraced-irrelevant reference
    # decode below (the shared factory traces everything it builds).
    acc = lc.accounting()
    assert acc["terminal_ok"] and acc["submitted"] == 6
    rep = lc.attribution_report()
    assert rep["reconcile_ok"] and rep["requests"] == 6
    assert rep["components"]["requeue"]["p99_ms"] > 0
    chains = {}
    for ev in lc.events():
        chains.setdefault(ev["id"], []).append(ev["kind"])
    assert killed_ids
    for rid in killed_ids:
        ks = chains[rid]
        assert ks.index("killed") < ks.index("requeued") \
            < ks.index("completed")
    # Per-replica attribution groups by the COMPLETING replica
    # (JSON-stable string keys).
    assert set(rep["per_replica"]) <= {"0", "1"}
    got = {c.request_id: np.asarray(c.tokens) for c in done}
    ref = reference_tokens(factory, vids)
    for i in range(6):
        np.testing.assert_array_equal(got[i], ref[i])


def test_replica_wedge_124_lifecycle_shows_retry_kill_requeue(setup):
    """The @replica=K fault axis consumed as an in-process 124: the
    wedged replica's residents carry retry -> killed -> requeued ->
    completed in the stream, with the books still balancing."""
    from cst_captioning_tpu.telemetry.lifecycle import LifecycleTracer

    plan = FaultPlan.parse("serve_wedge@replica=0")
    lc = LifecycleTracer()
    fleet, programs, factory = build_fleet(
        setup, 2, plan=plan, recover=True, retry_limit=0,
        rebuild_limit=0, lifecycle=lc)
    fleet.warm()
    vids = make_videos(4, seed=5)
    done = []
    for i, f in enumerate(vids):
        assert fleet.submit(i, f)
    done += fleet.run_until_idle()
    assert {c.request_id for c in done} == set(range(4))
    acc = lc.accounting()
    assert acc["terminal_ok"] and acc["submitted"] == 4
    assert lc.attribution_report()["reconcile_ok"]
    chains = {}
    for ev in lc.events():
        chains.setdefault(ev["id"], []).append(ev["kind"])
    wedged = [rid for rid, ks in chains.items() if "retry" in ks]
    assert wedged, "the injected wedge never hit a traced resident"
    for rid in wedged:
        ks = chains[rid]
        assert ks.index("retry") < ks.index("killed") \
            < ks.index("requeued") < ks.index("completed")
    ref = reference_tokens(factory, vids)
    got = {c.request_id: np.asarray(c.tokens) for c in done}
    for i in range(4):
        np.testing.assert_array_equal(got[i], ref[i])


def test_fleet_heartbeat_carries_per_replica(setup, tmp_path):
    """ISSUE-14 satellite pin: the fleet heartbeat file carries the
    per_replica health breakdown (via the server's pluggable health
    source), not just the worst-of-replicas status."""
    import time as _time

    from cst_captioning_tpu.utils.watchdog import ProgressWatchdog

    registry = MetricsRegistry()
    fleet, _, _ = build_fleet(setup, 2, registry=registry)
    fleet.warm()
    server = CaptionServer(fleet, vocab=None, feats_for=lambda v: None,
                           registry=registry, health_source=fleet.health)
    hb = tmp_path / "heartbeat.json"
    wd = ProgressWatchdog(
        0, describe=lambda: "fleet heartbeat pin",
        heartbeat_path=str(hb),
        payload=lambda: {"serving": server.health_payload(),
                         **registry.heartbeat_payload()},
        heartbeat_interval_s=0.05).start()
    try:
        deadline = _time.monotonic() + 10.0
        while not hb.exists() and _time.monotonic() < deadline:
            _time.sleep(0.02)
    finally:
        wd.stop()
    doc = json.loads(hb.read_text())
    per = doc["serving"]["per_replica"]
    assert {p["replica"] for p in per} == {0, 1}
    for p in per:
        assert p["status"] == "ok"
        assert "restarts" in p and "kills" in p and "recovery" in p
    # The fleet counters ride in the same payload (worst-of status +
    # detail + registry counters — one machine-auditable file).
    assert doc["serving"]["status"] == "ok"
    assert "fleet_routed" in doc["counters"]


# -- slow subprocess drill (make serve-fleet-chaos) ------------------------


@pytest.mark.slow
def test_cli_fleet_demo_under_replica_fault():
    """scripts/serve_fleet.py end to end: demo fleet of 2 under a
    replica-targeted wedge — every id answered, exit 0, fleet stats on
    stderr with the restart visible."""
    reqs = "".join(json.dumps({"id": i, "video_id": f"v{i}"}) + "\n"
                   for i in range(6)) + json.dumps({"op": "health"}) + "\n"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "serve_fleet.py"),
         "--serve_demo", "1", "--serve_replicas", "2",
         "--serve_demo_eos_bias", "-4",
         "--serve_retry_limit", "0", "--serve_rebuild_limit", "0",
         "--fault_plan", "serve_wedge@replica=0",
         "--loglevel", "WARNING"],
        input=reqs, capture_output=True, text=True, timeout=600,
        cwd=REPO, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines() if l.strip()]
    answered = {l["id"] for l in lines if "caption" in l}
    assert answered == set(range(6))
    health = [l for l in lines if l.get("op") == "health"]
    assert health and health[0]["replicas"] == 2
    stats_line = [l for l in proc.stderr.splitlines()
                  if l.startswith("serve_fleet: {")]
    assert stats_line, proc.stderr[-2000:]
    stats = json.loads(stats_line[0][len("serve_fleet: "):])
    assert stats["fleet"]["fleet_replica_restarts"] == 1
    assert stats["completed"] == 6
