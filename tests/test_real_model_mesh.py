"""Real-model data parallelism: CaptionModel steps on 1 vs 8 devices.

VERDICT.md round 1, weak #2: the suite only proved DP==single-device on a
toy regression; the real XE/rollout/RL steps crossed a mesh solely inside
``__graft_entry__.dryrun_multichip``, which no test invokes.  The pipeline
now lives in ``cst_captioning_tpu.parallel.dryrun.run_dp_pipeline`` —
shared verbatim with the driver's multichip artifact — and this module
asserts its 1-vs-8-device equivalence, so breaking a sharding annotation
in ``training/steps.py`` or ``parallel/`` fails the suite instead of only
the driver run.

Runs on the 8-device virtual CPU mesh (conftest.py) — SURVEY.md §4
"Distributed without a cluster" / "grad-psum equivalence to single-device".
"""

import jax
import numpy as np

from cst_captioning_tpu.parallel.dryrun import run_dp_pipeline

# One batch size divisible by both device counts under comparison, so both
# runs see bit-identical global inputs.
B = 8


class TestRealModelMesh:
    def test_xe_rollout_rl_equivalent_1_vs_8(self):
        r1 = run_dp_pipeline(1, batch_size=B, xe_steps=2)
        r8 = run_dp_pipeline(8, batch_size=B, xe_steps=2)
        assert r8["mesh_shape"]["data"] == 8
        # The 0.0-garble hardening (RESILIENCE.md caveat): the pipeline
        # retries deterministically through resilience/garble.all_zero
        # and SURFACES how many retries the result cost — assert the
        # ladder stayed within its bound instead of trusting stdout.
        # A clean attempt reports 0; a garbled machine reports 1-2 and
        # the equivalence asserts below still hold because retries are
        # bit-deterministic re-runs.
        for r in (r1, r8):
            assert 0 <= r["garble_retries"] <= 2, r["garble_retries"]
        np.testing.assert_allclose(r1["xe_losses"], r8["xe_losses"], rtol=1e-5)
        # The rollout is a deterministic function of (params, feats, key) in
        # the global view — sharding must not change which tokens come out.
        np.testing.assert_array_equal(r1["sampled"], r8["sampled"])
        np.testing.assert_array_equal(r1["greedy"], r8["greedy"])
        np.testing.assert_allclose(r1["rl_loss"], r8["rl_loss"], rtol=1e-5)
        # The fused on-device-reward step must also be mesh-invariant.
        np.testing.assert_allclose(r1["fused_loss"], r8["fused_loss"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(r1["fused_reward"], r8["fused_reward"],
                                   rtol=1e-5)
        flat1 = jax.tree.leaves(r1["params"])
        flat8 = jax.tree.leaves(r8["params"])
        assert len(flat1) == len(flat8)
        for a, b in zip(flat1, flat8):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)

    def test_xe_loss_finite_and_moves(self):
        r8 = run_dp_pipeline(8, xe_steps=3)
        assert all(np.isfinite(r8["xe_losses"]))
        assert r8["xe_losses"][-1] != r8["xe_losses"][0]
