#!/usr/bin/env python
"""Eval CLI — the reference ``test.py`` surface (SURVEY.md §3.3).

Loads a stage's BEST checkpoint (model hyperparams come from the
checkpoint's saved opts, not the CLI — reference semantics), decodes the
test split with the compiled beam search (``--beam_size``, 1 = greedy),
writes coco-format predictions + scores JSON, prints the metric table.

  python eval.py --checkpoint_path <dir> \\
      --test_feat_h5 ... --test_label_h5 ... --test_info_json ... \\
      --test_cocofmt_file ... --beam_size 5 --result_file scores.json
"""

from __future__ import annotations

import argparse
import json
import logging
import sys

import jax

from cst_captioning_tpu.data.dataset import CaptionDataset, SplitPaths
from cst_captioning_tpu.data.loader import CaptionLoader
from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.parallel.mesh import make_mesh
from cst_captioning_tpu.resilience.integrity import atomic_json_write
from cst_captioning_tpu.training.checkpoint import CheckpointManager
from cst_captioning_tpu.training.evaluation import eval_split
from cst_captioning_tpu.training.state import create_train_state, make_optimizer
from cst_captioning_tpu.training.trainer import build_model

log = logging.getLogger("cst_captioning_tpu.eval")


def load_model_for_eval(checkpoint_path: str, dataset: CaptionDataset,
                        cli_opt: argparse.Namespace,
                        cli_explicit: frozenset = frozenset()):
    """Rebuild the model from checkpoint infos and restore BEST params.

    Model hyperparams come from the checkpoint's saved opts, EXCEPT flags
    the user explicitly passed on this command line (``cli_explicit``) —
    an explicit ``--max_length`` must not be silently overridden by the
    training-time value."""
    # readonly: eval must never quarantine/scrub a training run's live
    # directory (torn steps are skipped by restore's verification anyway).
    ckpt = CheckpointManager(checkpoint_path, readonly=True)
    saved = ckpt.infos.get("opt")
    if saved:
        opt = argparse.Namespace(**{**vars(cli_opt), **{
            k: saved[k] for k in (
                "model_type", "rnn_size", "input_encoding_size", "num_layers",
                "att_size", "use_attention", "drop_prob", "num_heads",
                "num_tx_layers", "use_bfloat16", "max_length", "fusion_type",
            ) if k in saved and k not in cli_explicit
        }})
    else:
        log.warning("checkpoint has no saved opts; using CLI model flags")
        opt = cli_opt
    model = build_model(opt, dataset.vocab.size_with_pad, dataset.seq_length)
    tx, _ = make_optimizer()
    feat_shapes = list(zip(dataset.feat_times, dataset.feat_dims))
    state = create_train_state(model, jax.random.PRNGKey(0), feat_shapes,
                               dataset.seq_length, 1, tx)
    params = ckpt.restore_params(state.params, best=True)
    ckpt.close()
    return model, params, opt


def eval_via_serving_engine(model, params, loader, ds, opt, beat=None):
    """--engine serving: decode the split through the continuous-batching
    engine AND the legacy compiled decode, assert caption-for-caption
    equality, then score the serving predictions.  A mismatch is a FATAL
    parity break (exit 1 via the raised error) — the serving engine's
    whole contract is that it changes scheduling, never captions."""
    from cst_captioning_tpu.metrics.coco_eval import language_eval
    from cst_captioning_tpu.serving.buckets import parse_buckets
    from cst_captioning_tpu.serving.engine import serve_decode_split
    from cst_captioning_tpu.training.evaluation import decode_split

    kw = dict(max_len=opt.max_length, beam_size=opt.beam_size,
              length_norm=opt.length_norm,
              decode_chunk=getattr(opt, "decode_chunk", 0))
    legacy = decode_split(model, params, loader, ds.vocab, kw["max_len"],
                          beam_size=kw["beam_size"],
                          length_norm=kw["length_norm"], beat=beat,
                          decode_chunk=kw["decode_chunk"])
    serving = serve_decode_split(
        model, params, loader, ds.vocab, kw["max_len"],
        beam_size=kw["beam_size"], length_norm=kw["length_norm"],
        decode_chunk=kw["decode_chunk"],
        bucket_sizes=parse_buckets(getattr(opt, "serve_buckets", "1,4,8")),
        beat=beat)
    by_id = {p["image_id"]: p["caption"] for p in legacy}
    mismatch = [(p["image_id"], by_id.get(p["image_id"]), p["caption"])
                for p in serving if by_id.get(p["image_id"]) != p["caption"]]
    if len(serving) != len(legacy) or mismatch:
        detail = "; ".join(
            f"{vid}: legacy={a!r} serving={b!r}"
            for vid, a, b in mismatch[:5])
        raise RuntimeError(
            f"serving-engine parity FAILED: {len(mismatch)} of "
            f"{len(legacy)} captions differ from the legacy decode "
            f"({detail})")
    log.info("serving-engine parity: %d captions bit-identical to the "
             "legacy decode", len(serving))
    if beat is not None:
        beat()
    return serving, language_eval(serving, ds.references())


def main(argv=None) -> int:
    opt = parse_opts(argv)
    from cst_captioning_tpu.utils.platform import (configure_cli_logging,
                                                   enable_compile_cache)

    configure_cli_logging(opt.loglevel)

    enable_compile_cache(getattr(opt, "compile_cache_dir", ""))
    paths = SplitPaths(
        feat_h5=list(opt.test_feat_h5),
        label_h5=opt.test_label_h5,
        info_json=opt.test_info_json,
        cocofmt_json=opt.test_cocofmt_file,
    )
    raw = list(sys.argv[1:] if argv is None else argv)
    # Only decode-time knobs may override the checkpoint: architecture flags
    # must match the restored params regardless of what the CLI says.
    explicit = frozenset(
        a[2:].split("=", 1)[0] for a in raw if a.startswith("--")
    ) & {"max_length"}
    # Same wedge protection as the trainer (utils/watchdog.py): heartbeat
    # after the checkpoint restore, after every decoded batch, and between
    # decode and host scoring, so a dead transport exits 124 promptly
    # instead of hanging the eval.  As with training, --wedge_timeout must
    # exceed the longest single blocking call — the first beam compile
    # cannot beat mid-compile.
    from cst_captioning_tpu.utils.watchdog import ProgressWatchdog

    with ProgressWatchdog(
        getattr(opt, "wedge_timeout", 0.0) or 0.0,
        describe=lambda: f"eval of {opt.checkpoint_path}",
    ) as watchdog, CaptionDataset(paths) as ds:
        model, params, opt = load_model_for_eval(
            opt.checkpoint_path, ds, opt, cli_explicit=explicit)
        watchdog.beat()  # restore done
        loader = CaptionLoader(
            ds, batch_size=opt.eval_batch_size or opt.batch_size,
            seq_per_img=1, shuffle=False)
        if getattr(opt, "engine", "legacy") == "serving":
            # Serving-engine decode at batch-offline load, pinned
            # caption-for-caption against the legacy compiled decode —
            # the engine's end-to-end parity drill (SERVING.md).  Both
            # paths run single-device so the comparison is apples to
            # apples (the sharded legacy decode is pinned elsewhere).
            preds, scores = eval_via_serving_engine(
                model, params, loader, ds, opt, beat=watchdog.beat)
        else:
            mesh = make_mesh(jax.devices())  # decode shards over every chip
            preds, scores = eval_split(
                model, params, loader, ds.vocab, opt.max_length,
                ds.references(),
                beam_size=opt.beam_size, length_norm=opt.length_norm,
                mesh=mesh,
                beat=watchdog.beat,
                decode_chunk=getattr(opt, "decode_chunk", 0),
            )
    log.info("test scores: %s", {k: round(v, 4) for k, v in scores.items()})
    if opt.result_file:
        atomic_json_write(opt.result_file,
                          {"scores": scores, "predictions": preds}, indent=2)
        log.info("wrote %s", opt.result_file)
    print(json.dumps(scores))
    return 0


if __name__ == "__main__":
    sys.exit(main())
