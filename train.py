#!/usr/bin/env python
"""Train CLI — reference-compatible entry point (SURVEY.md §3.1/§3.2).

Runs one stage per invocation, like the reference ``train.py``:

  XE pretrain:   python train.py --train_feat_h5 ... --train_label_h5 ...
  WXE:           ... --use_consensus_weights 1 --train_bcmrscores_pkl ...
                 --start_from <xe checkpoint dir>
  CST/REINFORCE: ... --use_rl 1 --rl_baseline greedy|scb-sample|scb-gt
                 --start_from <wxe checkpoint dir>

See Makefile for the full three-stage recipe.
"""

from __future__ import annotations

import json
import sys

from cst_captioning_tpu.opts import parse_opts
from cst_captioning_tpu.parallel.dp import distributed_init
from cst_captioning_tpu.resilience.exitcodes import (EXIT_ADVANTAGE_ABORT,
                                                     EXIT_PREEMPTED)
from cst_captioning_tpu.resilience.preemption import (PreemptedExit,
                                                      PreemptionHandler)
from cst_captioning_tpu.training.trainer import NegativeAdvantageAbort, Trainer
from cst_captioning_tpu.utils.platform import (configure_cli_logging,
                                               enable_compile_cache)
from cst_captioning_tpu.utils.watchdog import ProgressWatchdog


def main(argv=None) -> int:
    """CLI entry.  Drivers that need the outcome read the stage's
    ``infos.json`` (scripts/scale_chain.py) or the JSON summary line this
    prints — both survive the subprocess boundary a wedge-recovery rerun
    needs, unlike an in-process return value."""
    opt = parse_opts(argv)
    configure_cli_logging(opt.loglevel)
    # Installed before the SLOW parts (backend init, Trainer construction,
    # feature-table uploads): a scheduler preemption landing anywhere in
    # bring-up must already find the checkpoint-and-exit handler armed
    # instead of dying mid-init with the default disposition.
    preemption = PreemptionHandler().install()
    enable_compile_cache(getattr(opt, "compile_cache_dir", ""))
    # distributed_init touches the backend before the Trainer's own
    # watchdog exists; cover it with a short-lived one so a coordinator
    # that never answers still produces exit 124, not a silent hang.
    with ProgressWatchdog(getattr(opt, "wedge_timeout", 0.0) or 0.0,
                          describe=lambda: "during distributed_init"):
        distributed_init(opt.coordinator_address,
                         opt.num_processes or None, opt.process_id)
    trainer = Trainer(opt, preemption=preemption)
    try:
        result = trainer.train()
    except NegativeAdvantageAbort as e:
        # Opt-in hard stop (--abort_on_negative_advantage_window): a
        # distinct exit code so an unattended chain can tell "stage
        # collapsing, reconfigure" (4) apart from crash (1) / wedge (124).
        print(json.dumps({"aborted": "negative_advantage_window",
                          "detail": str(e)}))
        return EXIT_ADVANTAGE_ABORT
    except PreemptedExit as e:
        # SIGTERM/SIGINT honored at a step boundary: the state is durable
        # (verified save, or the checkpoint already held this step), so
        # the stage harness restarts us as progress, not as a failure.
        print(json.dumps({"preempted": e.signal_name, "step": e.step,
                          "saved": e.saved,
                          "checkpoint_path": opt.checkpoint_path}))
        return EXIT_PREEMPTED
    finally:
        trainer.close()
    summary = {
        "best_score": result["best_score"],
        "best_step": result["best_step"],
        "last_step": result["last_step"],
        "eval_metric": opt.eval_metric,
        "checkpoint_path": opt.checkpoint_path,
    }
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
