# Experiment driver — the reference Makefile's role (SURVEY.md §2 L6):
# one target per stage, chained via --start_from, plus prepro/eval/bench.
#
# Real-data usage: point DATA at a directory holding the artifacts the
# prepro CLI builds (train/val/test {feat h5s, label h5, info json, cocofmt
# json} + train {ciderdf, consensus} pickles), set FEATS to the modality h5
# basenames, then `make xe wxe cst eval`.
#
# Zero-setup demo: `make demo` synthesizes a tiny dataset and runs the full
# XE -> WXE -> CST -> beam-eval pipeline on it (CPU-friendly).

PY        ?= python
DATA      ?= data
OUT       ?= checkpoints
EXP       ?= msrvtt
FEATS     ?= $(DATA)/train_resnet_feat.h5 $(DATA)/train_c3d_feat.h5
VAL_FEATS ?= $(DATA)/val_resnet_feat.h5 $(DATA)/val_c3d_feat.h5
TEST_FEATS?= $(DATA)/test_resnet_feat.h5 $(DATA)/test_c3d_feat.h5
BATCH     ?= 64
SEQ_PER_IMG ?= 20
BEAM      ?= 5

TRAIN_COMMON = \
  --train_feat_h5 $(FEATS) \
  --train_label_h5 $(DATA)/train_label.h5 \
  --train_info_json $(DATA)/train_info.json \
  --train_cocofmt_file $(DATA)/train_cocofmt.json \
  --val_feat_h5 $(VAL_FEATS) \
  --val_label_h5 $(DATA)/val_label.h5 \
  --val_info_json $(DATA)/val_info.json \
  --val_cocofmt_file $(DATA)/val_cocofmt.json \
  --batch_size $(BATCH) --seq_per_img $(SEQ_PER_IMG)

.PHONY: test lint lint-json chaos xe wxe cst cst_scb cst_host eval bench \
        demo trace-demo scale_chain report collect chip_window tune \
        tune-fast tune-report serve-demo serve-bench serve-stream-bench \
        serve-chaos serve-fleet-bench serve-fleet-chaos serve-proc-bench \
        serve-proc-chaos serve-trace-demo fleet-obs-demo bf16-parity \
        data-bench autoscale-bench autoscale-chaos journal-chaos \
        dataset-regen clean

# Default tier: everything except the `slow` subprocess chaos drills —
# the same selection the tier-1 verify uses; `make chaos` runs the rest.
test:
	$(PY) -m pytest tests/ -q -m 'not slow'

# Project-native static analysis (ANALYSIS.md): mechanically enforce the
# RESILIENCE.md/SERVING.md invariants — no device-scalar fetches in hot
# loops, durable JSON through atomic_json_write, counters declared at 0,
# exits through the taxonomy, no silent exception swallows, every
# donated jit buffer actually aliased — plus the CONCURRENCY contracts
# (reported as their own [concurrency] group): guarded_by/owned_by
# annotations, LOCK_ORDER embedding, signal-handler safety, named
# daemon-stated threads, monotonic deadlines.  Exit 0 = clean tree
# (every suppression carries a written justification); the same run
# rides in tier-1 via tests/test_cstlint.py.  `lint-json` emits the
# machine report that collect_evidence bundles into MANIFESTs.
lint:
	JAX_PLATFORMS=cpu $(PY) scripts/cstlint.py

lint-json:
	JAX_PLATFORMS=cpu $(PY) scripts/cstlint.py --json

# Chaos drills (RESILIENCE.md): drive the real trainer through injected
# faults — torn checkpoints, NaN gradients, loader errors, wedges, and
# PREEMPTION (a real SIGTERM via `preempt@step=N`: boundary save ->
# taxonomy exit 75 -> restart -> bit-exact resume) — and assert
# end-to-end recovery.  Includes the `slow` subprocess drills that the
# default `pytest -m 'not slow'` (tier-1) skips; the fast subsets of
# tests/test_resilience.py and tests/test_preemption.py (signal-flag,
# exit-code taxonomy, harness classification units) ride in tier-1
# automatically.
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py \
	  tests/test_preemption.py tests/test_watchdog.py -q

# -- three-stage recipe (XE -> WXE -> CST) --------------------------------

xe:
	$(PY) train.py $(TRAIN_COMMON) \
	  --checkpoint_path $(OUT)/$(EXP)_xe

wxe:
	$(PY) train.py $(TRAIN_COMMON) \
	  --start_from $(OUT)/$(EXP)_xe \
	  --use_consensus_weights 1 \
	  --train_bcmrscores_pkl $(DATA)/train_consensus.pkl \
	  --checkpoint_path $(OUT)/$(EXP)_wxe

cst:
	$(PY) train.py $(TRAIN_COMMON) \
	  --start_from $(OUT)/$(EXP)_wxe \
	  --use_rl 1 --rl_baseline greedy \
	  --train_cached_tokens $(DATA)/train_ciderdf.pkl \
	  --learning_rate 5e-5 \
	  --checkpoint_path $(OUT)/$(EXP)_cst

cst_scb:
	$(PY) train.py $(TRAIN_COMMON) \
	  --start_from $(OUT)/$(EXP)_wxe \
	  --use_rl 1 --rl_baseline scb-gt \
	  --train_bcmrscores_pkl $(DATA)/train_consensus.pkl \
	  --train_cached_tokens $(DATA)/train_ciderdf.pkl \
	  --learning_rate 5e-5 \
	  --checkpoint_path $(OUT)/$(EXP)_cst_scb

# cst/cst_scb above run the shipped default: reward computed ON DEVICE,
# the whole iteration one XLA program (--device_rewards 1, strict
# on-policy).  This target selects the host reward path instead — the
# reference's serial rollout -> host CIDEr-D -> grad semantics
# (--overlap_rewards 0; raise it to overlap host scoring with rollouts).
cst_host:
	$(PY) train.py $(TRAIN_COMMON) \
	  --start_from $(OUT)/$(EXP)_wxe \
	  --use_rl 1 --rl_baseline greedy --device_rewards 0 --overlap_rewards 0 \
	  --train_cached_tokens $(DATA)/train_ciderdf.pkl \
	  --learning_rate 5e-5 \
	  --checkpoint_path $(OUT)/$(EXP)_cst_host

eval:
	$(PY) eval.py \
	  --checkpoint_path $(OUT)/$(EXP)_cst \
	  --test_feat_h5 $(TEST_FEATS) \
	  --test_label_h5 $(DATA)/test_label.h5 \
	  --test_info_json $(DATA)/test_info.json \
	  --test_cocofmt_file $(DATA)/test_cocofmt.json \
	  --beam_size $(BEAM) \
	  --result_file $(OUT)/$(EXP)_cst_test_scores.json

# ActivityNet-style config: long I3D feature streams + Transformer decoder
# (driver config 5).  Same artifacts contract, different modality files.
anet_xe:
	$(PY) train.py $(TRAIN_COMMON) \
	  --model_type transformer --num_tx_layers 4 --num_heads 8 \
	  --checkpoint_path $(OUT)/$(EXP)_anet_xe

# Shipped-config benchmark.  DECODE_CHUNK/OVERLAP default to the trainer
# defaults read from opts.py; override to probe alternatives, e.g.
# `make bench DECODE_CHUNK=0` for the legacy full-length rollout scan.
DECODE_CHUNK ?=
OVERLAP      ?=
bench:
	$(PY) bench.py \
	  $(if $(DECODE_CHUNK),--decode_chunk $(DECODE_CHUNK),) \
	  $(if $(OVERLAP),--overlap_depth $(OVERLAP),)

# Rollout autotuner (tuning/): sweep decode_chunk/scan_unroll/overlap/
# device_rewards/decode_kernel/batch on the CURRENT backend and persist
# the winner as this platform's TUNED_CONFIGS.json entry, which train.py/
# eval.py/bench.py then resolve as defaults (explicit flags always win;
# PARITY.md "Tuned configs").  Deterministic + resumable: rerunning on an
# unchanged tree reuses the record without re-measuring.  `tune` is the
# full grid (slow, run it on the device you will train on); `tune-fast`
# is the 2-point CPU smoke sweep whose API equivalent rides in tier-1
# (tests/test_tuning.py).
tune:
	$(PY) scripts/tune.py

tune-fast:
	JAX_PLATFORMS=cpu $(PY) scripts/tune.py --fast \
	  --batch_size 4 --seq_per_img 4 --seq_len 12 --vocab 500 --hidden 32

tune-report:
	$(PY) scripts/tune_report.py

# Data-plane feed probe (ISSUE 15): the loader-only `bench.py --stage
# data` — batches/s + caps/s out of the real prefetcher at 4 assembler
# workers, the single-worker twin at the same seed, data_wait share at a
# simulated consumer running XE at the recorded 30k caps/s rate, and
# queue occupancy — summarized by scripts/data_report.py, which exits 1
# unless 4 workers sustain >= 2x the single-worker feed rate.  A fast
# CPU smoke like `tune-fast`; its API twin rides in tier-1
# (tests/test_data_plane.py).  Bare `python bench.py --stage data
# --loader_workers 4` measures the full default shape.
data-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --stage data --platform cpu --cache 0 \
	  --batch_size 8 --seq_per_img 4 --seq_len 16 --vocab 500 \
	  --loader_workers 4 --data_videos 32 --data_batches 24 \
	  --data_read_ms 6 > /tmp/cst_data_bench.json
	$(PY) scripts/data_report.py --file /tmp/cst_data_bench.json

# -- caption serving (SERVING.md) -----------------------------------------

# Zero-setup serving demo: pipe a few JSONL requests through the
# continuous-batching engine (tiny untrained EOS-biased model — captions
# are gibberish, the scheduling/backpressure/drain path is the real one).
serve-demo:
	printf '%s\n' \
	  '{"id": 1, "video_id": "v0"}' \
	  '{"id": 2, "video_id": "v1"}' \
	  '{"id": 3, "video_id": "v2"}' \
	  '{"id": 4, "video_id": "nope"}' \
	  '{"id": 5, "video_id": "v3"}' \
	| JAX_PLATFORMS=cpu $(PY) scripts/serve.py --serve_demo 1 --beam_size 1

# Serving load drills + the Poisson probe: the slow socket/SIGTERM-drain
# subprocess tests that tier-1 skips, then `bench.py --stage serving`
# (p50/p99 latency + captions/s, 0 recompiles after warmup asserted) at
# CPU-sized shapes, summarized as a latency table.  On a healthy device
# window run `python bench.py --stage serving` bare for the full-shape
# cached number.
serve-bench:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serving.py \
	  tests/test_serving_stream.py -q
	JAX_PLATFORMS=cpu $(PY) bench.py --stage serving --platform cpu --cache 0 \
	  --batch_size 8 --seq_per_img 2 --seq_len 16 --vocab 500 --hidden 64 \
	  --serve_requests 12 --serve_rate 6 > /tmp/cst_serve_bench.json
	$(PY) scripts/serve_report.py --file /tmp/cst_serve_bench.json

# Streaming + result-cache probe (SERVING.md "Streaming & result
# cache"): the zipfian open-loop Poisson probe with streaming ON and the
# exact-result cache armed, plus its cache-OFF twin in the same run.
# The probe itself asserts zero post-warmup compiles and stream prefix
# consistency (a violation raises, so no JSON line is emitted);
# serve_report renders TTFT / inter-chunk-gap / hit-rate rows and exits
# 1 if any cache hit is not bit-identical to its miss twin, or the
# cached run does not beat the twin on captions/s.  The fast API slice
# of this probe rides tier-1 (tests/test_serving_stream.py).
serve-stream-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --stage serving --platform cpu --cache 0 \
	  --batch_size 8 --seq_per_img 2 --seq_len 16 --vocab 500 --hidden 64 \
	  --serve_requests 32 --serve_rate 300 --serve_stream 1 --serve_cache 16 \
	  --serve_unique 4 --serve_zipf 1.1 --serve_cache_compare 1 \
	  --probe_eos_bias -4 \
	  > /tmp/cst_serve_stream.json
	$(PY) scripts/serve_report.py --file /tmp/cst_serve_stream.json

# bf16 decode parity gate (ops/bf16_decode.py): CIDEr delta vs the fp32
# decode of the same checkpoint, bounded; exit 1 (with 'reference'
# pinned as the recommendation) outside the bound.  Bare target = the
# zero-setup synthetic smoke; run against a real checkpoint with the
# eval-style --checkpoint_path/--test_* flags for the record of
# evidence.
bf16-parity:
	JAX_PLATFORMS=cpu $(PY) scripts/bf16_parity.py --synthetic 1 \
	  --max_length 8 --beam_size 2 --loglevel WARNING

# Serving chaos drills (RESILIENCE.md "Serving faults"): the seeded
# serve_wedge/serve_garble/admit_err fault plans through the self-healing
# scheduler — captions bit-identical to the fault-free twin, zero
# post-warmup compiles including across an engine rebuild, counters
# reflecting every injected fault — plus the deadline/TTL eviction units
# and the double-SIGTERM drain drill.  Includes the `slow` subprocess
# drills tier-1 skips; the fast slice rides in tier-1 automatically.
# CST_LOCK_SANITIZER=1 arms the runtime lock sanitizer (analysis/
# locksan.py) in-process AND in the subprocess drills: the declared
# LOCK_ORDER is re-validated under every injected fault, and any
# inversion/undeclared nesting fails the drill with a durable receipt.
serve-chaos:
	CST_LOCK_SANITIZER=1 JAX_PLATFORMS=cpu \
	  $(PY) -m pytest tests/test_serving_resilience.py tests/test_locksan.py -q

# Fleet probe (SERVING.md "Fleet"): the open-loop Poisson stream through
# the health-aware router over 3 replicas with a mid-stream hard replica
# kill/restart — caps/s/fleet + p99 under kill/restart in the JSON line;
# the probe itself asserts zero post-warmup compiles fleet-wide
# (including through the restart) and serve_report exits 1 unless every
# fleet caption is bit-identical to the fault-free single-engine run.
serve-fleet-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --stage serving --platform cpu --cache 0 \
	  --batch_size 8 --seq_per_img 2 --seq_len 16 --vocab 500 --hidden 64 \
	  --serve_requests 24 --serve_rate 200 --replicas 3 \
	  --serve_kill_replica 1 --probe_eos_bias -2 \
	  > /tmp/cst_serve_fleet.json
	$(PY) scripts/serve_report.py --file /tmp/cst_serve_fleet.json

# Fleet chaos drills (SERVING.md "Fleet", RESILIENCE.md "@replica=K"):
# replica-targeted serve_wedge/serve_garble/admit_err plans through the
# router, the hard kill/restart with resident re-queue, draining
# rotation, fleet-edge shed — every request answered, captions
# bit-identical to the fault-free single-engine twin, zero post-warmup
# compiles fleet-wide.  Includes the slow serve_fleet.py subprocess
# drills tier-1 skips; the fast slice rides tier-1 sanitizer-armed.
serve-fleet-chaos:
	CST_LOCK_SANITIZER=1 JAX_PLATFORMS=cpu \
	  $(PY) -m pytest tests/test_serving_fleet.py -q
	JAX_PLATFORMS=cpu $(PY) bench.py --stage serving --platform cpu --cache 0 \
	  --batch_size 8 --seq_per_img 2 --seq_len 16 --vocab 500 --hidden 64 \
	  --serve_requests 24 --serve_rate 200 --replicas 3 \
	  --serve_kill_replica 1 --probe_eos_bias -2 \
	  --serve_trace 1 \
	  --serve_blackbox /tmp/cst_serve_fleet_chaos_blackbox.json \
	  > /tmp/cst_serve_fleet_chaos.json
	$(PY) scripts/serve_report.py --file /tmp/cst_serve_fleet_chaos.json

# Process-fleet probe (SERVING.md "Process fleet"): the seeded chaos
# drill through scripts/serve_supervisor.py — 3 real serve.py child
# processes, SIGKILL replica 1 mid-stream, crash-proof requeue.  The
# probe itself exits 1 unless every request is answered, captions are
# bit-identical to the fault-free single-engine reference, surviving
# children report zero post-warmup compiles, the killed child's
# blackbox was harvested into an incident bundle, and no SLO burn-rate
# alert is left firing (loose objectives armed below — the gate proves
# the monitor ran, not that the drill was fast); serve_report re-gates
# the record (restart budget, bit-identity, SLO).
serve-proc-bench:
	rm -rf /tmp/cst_supervise && \
	JAX_PLATFORMS=cpu $(PY) scripts/serve_supervisor.py --serve_demo 1 \
	  --supervise_probe 1 --supervise_replicas 3 \
	  --serve_demo_eos_bias -2 --decode_chunk 2 --beam_size 1 \
	  --slo_p99_ms 60000 --slo_availability 0.5 \
	  --supervise_dir /tmp/cst_supervise \
	  > /tmp/cst_serve_proc.json
	$(PY) scripts/serve_report.py --file /tmp/cst_serve_proc.json

# Process-fleet chaos drills (SERVING.md "Process fleet", RESILIENCE.md
# "Process faults"): the full tests/test_supervisor.py suite including
# the slow real-subprocess drills tier-1 skips (proc_kill requeue,
# double-SIGTERM supervisor drain, the CLI probe), sanitizer-armed,
# then the probe + report gates above.
serve-proc-chaos:
	CST_LOCK_SANITIZER=1 JAX_PLATFORMS=cpu \
	  $(PY) -m pytest tests/test_supervisor.py -q
	$(MAKE) serve-proc-bench

# Autoscaler burst drill (SERVING.md "Autoscaling & brownout"): the
# seeded 3-phase probe (idle -> 4x burst -> idle) through
# scripts/serve_supervisor.py — starts at --autoscale_min children,
# must scale up within the scrape-interval budget, drain back down,
# answer EVERY request exactly once bit-identical to the fault-free
# single-engine reference, and keep surviving children at zero
# post-warmup compiles.  serve_report re-gates the probe record
# (started_at_min / scaled_up / scaled_down / no_thrash / answered_ok)
# and fleet_report gates the scraped series (scale-event loss, thrash,
# brownout p99) plus renders the replica timeline.
autoscale-bench:
	rm -rf /tmp/cst_autoscale && \
	JAX_PLATFORMS=cpu $(PY) scripts/serve_supervisor.py --serve_demo 1 \
	  --autoscale_probe 1 --autoscale_min 1 --autoscale_max 3 \
	  --autoscale_up_cooldown_s 1 --autoscale_down_cooldown_s 1 \
	  --serve_demo_eos_bias -2 --decode_chunk 2 --beam_size 1 \
	  --fleet_scrape_ms 200 --serve_lifecycle 1 \
	  --slo_p99_ms 60000 --slo_availability 0.5 \
	  --supervise_dir /tmp/cst_autoscale \
	  > /tmp/cst_autoscale.json
	$(PY) scripts/serve_report.py --file /tmp/cst_autoscale.json
	$(PY) scripts/fleet_report.py --dir /tmp/cst_autoscale

# Autoscaler chaos (SERVING.md "Autoscaling & brownout"): the full
# tests/test_autoscale.py suite sanitizer-armed — including the slow
# real-subprocess drills tier-1 skips (SIGKILL mid-scale-event, the
# CLI burst probe) — then the bench drill + report gates above.
autoscale-chaos:
	CST_LOCK_SANITIZER=1 JAX_PLATFORMS=cpu \
	  $(PY) -m pytest tests/test_autoscale.py -q
	$(MAKE) autoscale-bench

# Durable-intake-journal chaos (SERVING.md "Durable intake journal"):
# the full tests/test_journal.py suite sanitizer-armed — the torn-tail
# byte-boundary sweep, duplicate suppression, the in-process
# supervisor-death replay drill, plus the slow real-subprocess probe
# tier-1 skips — then the CLI drill itself: SIGKILL the SUPERVISOR
# (whole process group) mid-storm with streams in flight, relaunch on
# the same journal dir, and gate the record with serve_report
# (exactly-once / replay accounting / dup suppression / torn tail) and
# the run dir with fleet_report (journal coverage cross-check against
# the exit snapshot's high-water mark; the blackout gate is relaxed —
# the scrape gap between the two supervisor incarnations IS the
# deliberate SIGKILL window).
journal-chaos:
	CST_LOCK_SANITIZER=1 JAX_PLATFORMS=cpu \
	  $(PY) -m pytest tests/test_journal.py -q
	rm -rf /tmp/cst_journal && \
	JAX_PLATFORMS=cpu $(PY) scripts/serve_supervisor.py --serve_demo 1 \
	  --journal_probe 1 --supervise_replicas 2 \
	  --serve_demo_eos_bias -2 --decode_chunk 2 --beam_size 1 \
	  --slo_p99_ms 60000 --slo_availability 0.5 \
	  --supervise_dir /tmp/cst_journal \
	  > /tmp/cst_serve_journal.json
	$(PY) scripts/serve_report.py --file /tmp/cst_serve_journal.json
	$(PY) scripts/fleet_report.py --dir /tmp/cst_journal \
	  --blackout_factor 1000

# Fleet-observability demo (OBSERVABILITY.md "Fleet plane"): the
# seeded 3-child supervised drill with the scraper on a 200 ms cadence
# and loose SLO objectives armed, then (1) stitch the supervisor's and
# every child's trace into ONE clock-skew-corrected Perfetto file
# (scripts/fleet_trace.py — per-request async tracks cross the process
# boundary), (2) render it with trace_report's merged-trace view, and
# (3) gate the scraped series with fleet_report — exit 1 on a burn-rate
# violation, a scrape blackout, or a replica-slot coverage hole.
# Artifacts under /tmp/cst_fleet_obs: fleet_trace.json (load in
# Perfetto), fleet_metrics.jsonl, clock_sync.json, slo_alerts.jsonl,
# trace/ + replica<K>/trace/.
fleet-obs-demo:
	rm -rf /tmp/cst_fleet_obs && \
	JAX_PLATFORMS=cpu $(PY) scripts/serve_supervisor.py --serve_demo 1 \
	  --supervise_probe 1 --supervise_replicas 3 \
	  --serve_demo_eos_bias -2 --decode_chunk 2 --beam_size 1 \
	  --fleet_scrape_ms 200 --slo_p99_ms 60000 --slo_availability 0.5 \
	  --supervise_dir /tmp/cst_fleet_obs \
	  > /tmp/cst_fleet_obs.json
	$(PY) scripts/fleet_trace.py --dir /tmp/cst_fleet_obs
	$(PY) scripts/trace_report.py --trace_dir /tmp/cst_fleet_obs
	$(PY) scripts/fleet_report.py --dir /tmp/cst_fleet_obs
	$(PY) scripts/serve_report.py --file /tmp/cst_fleet_obs.json

# Zero-setup request-lifecycle drill (OBSERVABILITY.md "Request
# lifecycle & flight recorder"): pipe a few requests (plus the
# {"op": "stats"} and {"op": "dump"} wire ops) through the demo backend
# with span tracing AND the lifecycle tracer armed, then render the
# per-request waterfall — the Chrome trace's async request tracks plus
# the duration spans — with trace_report.  Artifacts: the Perfetto-
# loadable trace_*.json, blackbox.json (on-demand dump), and the
# telemetry.json exit snapshot, all under /tmp/cst_serve_trace_demo.
serve-trace-demo:
	rm -rf /tmp/cst_serve_trace_demo && mkdir -p /tmp/cst_serve_trace_demo
	printf '%s\n' \
	  '{"id": 1, "video_id": "v0"}' \
	  '{"id": 2, "video_id": "v1"}' \
	  '{"id": 3, "video_id": "v2"}' \
	  '{"op": "stats"}' \
	  '{"op": "dump"}' \
	| JAX_PLATFORMS=cpu $(PY) scripts/serve.py --serve_demo 1 --beam_size 1 \
	  --trace_dir /tmp/cst_serve_trace_demo/trace \
	  --serve_blackbox /tmp/cst_serve_trace_demo/blackbox.json \
	  --serve_telemetry_file /tmp/cst_serve_trace_demo/telemetry.json
	$(PY) scripts/trace_report.py \
	  --trace_dir /tmp/cst_serve_trace_demo/trace \
	  --json /tmp/cst_serve_trace_demo/trace_summary.json

# -- zero-setup synthetic demo --------------------------------------------

demo:
	$(PY) scripts/demo.py --out_dir /tmp/cst_demo

# Telemetry demo (OBSERVABILITY.md): short CPU train with --trace_dir,
# then the scripts/trace_report.py per-phase table.  Artifacts land in
# /tmp/cst_trace_demo (Chrome traces, metrics.jsonl, telemetry.json).
trace-demo:
	JAX_PLATFORMS=cpu $(PY) scripts/trace_demo.py --out_dir /tmp/cst_trace_demo

# MSR-VTT-scale synthetic chain (640 videos x 20 captions, ~8k vocab,
# ResNet+C3D shapes): XE-to-convergence -> WXE -> CST (fused rewards) ->
# beam-5 eval, stage-resumable.  scripts/scale_chain.py --help for knobs.
scale_chain:
	$(PY) scripts/scale_chain.py --out_dir /tmp/cst_scale \
	  --num_videos 6513 --num_val 497 --lr_decay_every 10 \
	  --stages xe,wxe,cst,cst_scb_sample,eval

# Prove a post-/tmp-wipe dataset rebuild is THE dataset the committed
# evidence was trained on: regenerate the north-star labels + vocab in
# a fresh temp dir via the chain's own recipe and compare content
# hashes (HDF5-mtime-proof) against the committed
# artifacts/dataset_fingerprint.json — exit 1 on any drift.  After a
# DELIBERATE spec/grammar change, refresh the record with
# `$(PY) scripts/dataset_fingerprint.py --update`.
dataset-regen:
	JAX_PLATFORMS=cpu $(PY) scripts/dataset_fingerprint.py --check

# Chain status + learning curves + beam tables for the dir above.
report:
	$(PY) scripts/chain_report.py --out_dir /tmp/cst_scale

# Snapshot the chain's durable evidence into artifacts/<NAME>.
collect:
	$(PY) scripts/collect_evidence.py --out_dir /tmp/cst_scale \
	  --name $(or $(NAME),cst_scale)

# Wait for the next healthy-tunnel window, then capture perf evidence
# (phase costs, bench cache refresh, fused-step trace) automatically.
chip_window:
	$(PY) scripts/chip_window.py --out_dir /tmp/chip_window

clean:
	rm -rf $(OUT)
