#!/usr/bin/env python
"""Throughput benchmark — captions/sec/chip, XE and CST train stages.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "captions/s/chip", "vs_baseline": N}

By default BOTH stages are measured and the headline value is the MIN of
the two, so the artifact can't pass on the easy stage alone (--stage xe or
cst isolates one).  The CST stage headlines the shipped trainer
configuration — the fused on-device reward path (--device_rewards 1,
rollout + CIDEr-D + grad as ONE XLA program) — and also measures and
reports the host reward path (native C++ scorer + overlapped pipeline at
the trainer's --overlap_rewards default) and the strictly serial
reference-semantics loop.

Baseline: the driver north-star of >= 5000 captions/sec/chip for the XE and
CST stages on MSR-VTT-shaped data (BASELINE.md; the reference published no
throughput numbers — SURVEY.md §6).  ``vs_baseline`` is value/5000.

Shapes mirror MSR-VTT training: ResNet-152 (28, 2048) + C3D (1, 4096)
features, vocab ~8k, 30-token captions, 20 captions/video, attention-LSTM
decoder (hidden 512).  Data is synthetic and device-resident so the number
measures the compiled step, not disk IO (the loader's prefetch thread hides
IO in real training; see cst_captioning_tpu/data/loader.py).

Backend robustness: the default jax backend in this environment can be a
remote-TPU PJRT plugin whose tunnel client blocks forever when the tunnel
is down (round 1's driver bench died exactly there, rc=1/hang).  main()
therefore first PROBES the default backend in a subprocess with a timeout
(+retries), then runs the measurement in a child process — on the probed
device backend if it answered, else on the host CPU with a scrubbed
environment.  The JSON line always reports which platform actually ran
(``platform`` key) so a CPU fallback can't masquerade as a TPU number.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

# FLOPs/MFU accounting is shared with the trainer's live mfu_pct gauge
# (cst_captioning_tpu/telemetry/flops.py — pure math, no jax import, so
# the probe-before-backend ordering below is preserved).  These names
# stay re-exported here for bench's existing callers/tests.
from cst_captioning_tpu.telemetry.flops import (  # noqa: F401
    PEAK_BF16_TFLOPS,
    caption_step_flops,
    mfu_fields,
    peak_tflops,
)
from cst_captioning_tpu.resilience.exitcodes import (
    EXIT_FAILURE,
    EXIT_OK,
)
from cst_captioning_tpu.resilience.integrity import atomic_json_write

BASELINE_CAPTIONS_PER_SEC = 5000.0


def analytic_step_flops(args) -> dict:
    """Analytic step FLOPs at this run's CLI shapes — the MSR-VTT bench
    feature geometry (telemetry.flops.DEFAULT_FEAT_SHAPES) mirroring
    build().  -> {"xe": F, "cst": F}."""
    return caption_step_flops(args.batch_size, args.seq_per_img,
                              args.seq_len, args.vocab, args.hidden)


def build(batch: int, seq_per_img: int, seq_len: int, vocab: int,
          hidden: int, use_bfloat16: bool, scan_unroll: int | None = None,
          decode_kernel: str | None = None):
    import jax
    import jax.numpy as jnp

    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.opts import (
        DEFAULT_DECODE_KERNEL,
        DEFAULT_REMAT_CELL,
        DEFAULT_SCAN_UNROLL,
    )
    from cst_captioning_tpu.training.state import create_train_state, make_optimizer

    model = CaptionModel(
        vocab_size=vocab, embed_size=hidden, hidden_size=hidden,
        attn_size=hidden, use_attention=True, dropout_rate=0.5,
        dtype=jnp.bfloat16 if use_bfloat16 else jnp.float32,
        scan_unroll=(DEFAULT_SCAN_UNROLL if scan_unroll is None
                     else scan_unroll),
        decode_kernel=decode_kernel or DEFAULT_DECODE_KERNEL,
        remat_cell=bool(DEFAULT_REMAT_CELL),
    )
    tx, _ = make_optimizer(learning_rate=2e-4, grad_clip=10.0)
    feat_shapes = [(28, 2048), (1, 4096)]
    state = create_train_state(
        model, jax.random.PRNGKey(0), feat_shapes, seq_len, seq_per_img, tx,
        batch_size=batch,
    )
    rng = np.random.default_rng(0)
    feats = [
        jnp.asarray(rng.standard_normal((batch, t, d)), jnp.float32)
        for t, d in feat_shapes
    ]
    labels = jnp.asarray(
        rng.integers(1, vocab, (batch * seq_per_img, seq_len)), jnp.int32
    )
    # realistic 0-termination: captions average ~10 tokens
    lens = rng.integers(6, seq_len - 1, batch * seq_per_img)
    labels = jnp.asarray(np.where(
        np.arange(seq_len)[None, :] < lens[:, None], np.asarray(labels), 0
    ), jnp.int32)
    return model, state, feats, labels


def synthetic_rewarder(batch: int, seq_per_img: int, vocab_size: int,
                       native: bool = True):
    """Vocab + synthetic 20-refs-per-video corpus + CIDEr-D scorer +
    RewardComputer — the CST reward scaffolding shared by ``bench_cst`` and
    the ``scripts/`` probes so their measurements can't drift apart.

    Returns (reward_computer, video_ids, scorer_kind) where scorer_kind is
    "native" or "python" (fallback when the C++ build is unavailable).
    """
    from cst_captioning_tpu.data.vocab import Vocab
    from cst_captioning_tpu.training.rewards import RewardComputer

    vocab = Vocab({i: f"w{i}" for i in range(1, vocab_size)})
    rng = np.random.default_rng(1)
    refs = {
        f"v{i}": [
            " ".join(f"w{w}" for w in rng.integers(1, vocab_size, 10))
            for _ in range(20)
        ]
        for i in range(batch)
    }
    scorer = None
    scorer_kind = "python"
    if native:
        try:
            from cst_captioning_tpu.native import NativeCiderD

            scorer = NativeCiderD(refs, vocab.word_to_ix)
            scorer_kind = "native"
        except Exception as e:
            print(f"bench: native CIDEr-D unavailable ({e}); using Python",
                  file=sys.stderr)
    if scorer is None:
        from cst_captioning_tpu.metrics.ciderd import CiderD, build_corpus_df

        df, n = build_corpus_df(refs)
        scorer = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    rc = RewardComputer(vocab, scorer, refs, seq_per_img=seq_per_img,
                        baseline="greedy")
    return rc, list(refs.keys()), scorer_kind, refs, vocab


def resolve_axes(args) -> tuple[dict, dict, dict | None]:
    """Resolve the five tunable rollout axes for THIS run.

    -> (axes, sources, tuning_provenance): per axis the value and where it
    came from — "flag" (explicit CLI), "record" (the platform's tuning
    record, tuning/record.py), or "default" (the opts.py built-in).  The
    same flag > record > built-in order ``opts.parse_opts`` applies to the
    trainer, so bare ``python bench.py`` measures exactly the configuration
    a bare ``python train.py`` would run.
    """
    from cst_captioning_tpu.opts import (
        DEFAULT_DECODE_CHUNK,
        DEFAULT_DECODE_KERNEL,
        DEFAULT_DEVICE_REWARDS,
        DEFAULT_OVERLAP_REWARDS,
        DEFAULT_SCAN_UNROLL,
    )
    from cst_captioning_tpu.tuning.record import resolved_tuned_defaults

    tuned, provenance = resolved_tuned_defaults()
    builtin = {
        "decode_chunk": DEFAULT_DECODE_CHUNK,
        "scan_unroll": DEFAULT_SCAN_UNROLL,
        "overlap_rewards": DEFAULT_OVERLAP_REWARDS,
        "device_rewards": DEFAULT_DEVICE_REWARDS,
        "decode_kernel": DEFAULT_DECODE_KERNEL,
    }
    argname = {"overlap_rewards": "overlap_depth"}  # bench's historical name
    axes, sources = {}, {}
    for axis, default in builtin.items():
        value = getattr(args, argname.get(axis, axis), None)
        if value is not None:
            axes[axis], sources[axis] = value, "flag"
        elif axis in tuned:
            axes[axis], sources[axis] = tuned[axis], "record"
        else:
            axes[axis], sources[axis] = default, "default"
    return axes, sources, provenance


def tuning_fields(args) -> dict:
    """The tuned-provenance JSON fields (ISSUE 6 satellite): ``tuned`` is
    True only when at least one axis actually resolved from a tuning
    record, and then ``tuning_record``/``tuned_axes`` say which record and
    which values — a hand-flagged run can never be confused with a tuned
    one."""
    axes, sources, provenance = resolve_axes(args)
    from_record = sorted(a for a, s in sources.items() if s == "record")
    fields: dict = {"tuned": bool(from_record), "tuning_record": None}
    if from_record and provenance is not None:
        fields["tuning_record"] = provenance.get("record")
        fields["tuned_axes"] = {a: axes[a] for a in from_record}
        fields["tuning_git_sha_matches_head"] = provenance.get(
            "git_sha_matches_head")
    return fields


def bench_xe(args):
    import jax
    import jax.numpy as jnp

    from cst_captioning_tpu.training.steps import make_xe_step

    axes, _, _ = resolve_axes(args)
    model, state, feats, labels = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16, scan_unroll=axes["scan_unroll"],
    )
    weights = jnp.ones((args.batch_size * args.seq_per_img,))
    step = jax.jit(make_xe_step(model, args.seq_per_img), donate_argnums=(0,))
    rng = jax.random.PRNGKey(0)

    # Barriers are VALUE fetches, not block_until_ready: the scalar fetch
    # is unconditionally trustworthy on any backend (the value must exist
    # to be returned).  One round-3 run on the remote-TPU tunnel produced a
    # ~20x-inflated timing with block_until_ready as the barrier; whether
    # that was a barrier bug or dispatch/transfer asymmetry on the tunnel
    # is unconfirmed — the value fetch sidesteps the question entirely.
    state, m = step(state, feats, labels, weights, rng)       # compile
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = step(state, feats, labels, weights, rng)
    float(m["loss"])
    dt = time.perf_counter() - t0
    return args.batch_size * args.seq_per_img * args.steps / dt


def rollout_step_probe(model, state, feats, args, decode_chunk: int) -> dict:
    """Early-exit accounting probe (NOT a throughput number): how many
    decode steps does the rollout actually execute under --decode_chunk,
    versus the legacy full-length scan's unconditional ``seq_len``?

    The bench model is untrained, so its multinomial rollout essentially
    never draws EOS and early exit cannot fire on the throughput loops
    above.  A CONVERGED captioning policy terminates nearly every caption
    in ~7-10 of the 30 steps (PARITY.md length evidence) — the probe
    simulates exactly that by biasing the vocab head's EOS logit
    (``--probe_eos_bias``) so the whole batch terminates early, then
    reports the executed-step counter the chunked scan returns alongside
    the sampled-length histogram, so the saving can be read against the
    lengths that produced it.  Runs once, untimed; the throughput numbers
    in this JSON are unaffected.
    """
    import jax
    import numpy as np

    from cst_captioning_tpu.ops.losses import sequence_mask
    from cst_captioning_tpu.ops.sampling import sample_with_baseline

    params = {**state.params}
    params["logit"] = {**params["logit"]}
    params["logit"]["bias"] = (
        params["logit"]["bias"].at[0].add(args.probe_eos_bias))

    def probe(params, feats, rng, chunk):
        sampled, _, _, steps = sample_with_baseline(
            model, {"params": params}, feats, rng, args.seq_len,
            args.seq_per_img, decode_chunk=chunk, return_steps=True,
        )
        return sampled, steps

    rng = jax.random.PRNGKey(777)
    sampled, steps = jax.jit(probe, static_argnums=(3,))(
        params, feats, rng, decode_chunk)
    lens = np.asarray(sequence_mask(sampled).sum(axis=1))
    executed = int(steps)
    return {
        "eos_bias": args.probe_eos_bias,
        "steps_legacy": args.seq_len,
        "steps_executed": executed,
        "steps_saved_pct": round(100.0 * (1 - executed / args.seq_len), 1),
        "len_mean": round(float(lens.mean()), 2),
        "len_p50": float(np.percentile(lens, 50)),
        "len_max": float(lens.max()),
    }


def bench_cst(args, paths: tuple = ("host", "serial", "fused"),
              probe: bool = True):
    """CST iteration throughput in the SHIPPED trainer configuration.

    The shipped default (--device_rewards 1, opts.DEFAULT_DEVICE_REWARDS)
    fuses rollout + on-device CIDEr-D + REINFORCE grad into ONE XLA
    program; that path is the headline CST number.  The host reward path
    (C++ scorer + overlapped pipeline at the trainer's --overlap_rewards
    default, plus the strictly serial reference-semantics loop) is
    measured and reported alongside — and becomes the headline when
    --device_rewards 0 is passed or the fused path cannot execute on this
    backend (then labeled ``cst_path: host_pipeline_fallback``).

    Every rollout axis (--decode_chunk, --scan_unroll, --decode_kernel,
    depth, device_rewards) resolves flag > tuning record > built-in
    (``resolve_axes``), so bare ``python bench.py`` measures the tuned
    shipped configuration.

    ``paths`` selects which of {"host", "serial", "fused"} to measure —
    the autotuner (tuning/sweep.py) pays for exactly one path per sweep
    point; the full bench measures all three.  Unmeasured paths report
    None.  ``probe=False`` skips the untimed early-exit accounting probe.
    """
    import jax

    axes, _, _ = resolve_axes(args)
    model, state, feats, labels = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16, scan_unroll=axes["scan_unroll"],
        decode_kernel=axes["decode_kernel"],
    )
    rc, video_ids, scorer_kind, refs, vocab = synthetic_rewarder(
        args.batch_size, args.seq_per_img, args.vocab,
        native=bool(args.native_cider),
    )
    ncaps = args.batch_size * args.seq_per_img
    dc = axes["decode_chunk"]
    depth = axes["overlap_rewards"]
    want_fused = axes["device_rewards"]

    overlapped = serial = None
    if "host" in paths or "serial" in paths:
        from cst_captioning_tpu.training.pipeline import RewardPipeline
        from cst_captioning_tpu.training.steps import (
            make_rl_grad_step,
            make_rollout_fused,
        )

        rollout = jax.jit(make_rollout_fused(
            model, args.seq_len, args.seq_per_img, decode_chunk=dc))
        rl_step = jax.jit(make_rl_grad_step(model, args.seq_per_img),
                          donate_argnums=(0,))

        def run_loop(state, depth, steps, key0):
            # The EXACT shipped pipeline: bench and trainer drive the same
            # class.
            pipe = RewardPipeline(
                rollout, rl_step,
                lambda ctx, s, g: rc(ctx, s, g), depth,
            )
            last = None
            for i in range(steps):
                key = jax.random.PRNGKey(key0 + i)
                state, done = pipe.push(state, feats, key, key, video_ids)
                if done:
                    last = done[-1]
            state, done = pipe.drain(state)
            if done:
                last = done[-1]
            # value fetch: trustworthy barrier (see bench_xe)
            float(last[1]["loss"])
            return state

        state = run_loop(state, depth, 2, 0)                   # compile/warm
        if "host" in paths:
            t0 = time.perf_counter()
            state = run_loop(state, depth, args.steps, 100)
            overlapped = ncaps * args.steps / (time.perf_counter() - t0)
        if "serial" in paths:
            t0 = time.perf_counter()
            state = run_loop(state, 0, args.steps, 200)
            serial = ncaps * args.steps / (time.perf_counter() - t0)

    # Fully-fused on-device reward path (--device_rewards 1): rollout +
    # CIDEr-D + grad as ONE program, strict on-policy, zero host boundary.
    # Imports/table build run OUTSIDE the try so a code regression fails
    # loudly; only backend execution failures (compile/OOM on an exotic
    # device) degrade to fused=null without sinking the headline above.
    fused_cps = None
    if "fused" in paths:
        from cst_captioning_tpu.training.device_rewards import (
            build_device_tables,
        )
        from cst_captioning_tpu.training.steps import make_fused_cst_step

        corpus, tables, _ = build_device_tables(refs, vocab.word_to_ix)
        step_fn = make_fused_cst_step(model, args.seq_len, args.seq_per_img,
                                      corpus, tables, decode_chunk=dc)
        fused = jax.jit(step_fn, donate_argnums=(0,))
        vix = np.arange(args.batch_size, dtype=np.int32)
        # Trace OUTSIDE the try: a code regression in the fused step fails
        # loudly here; only backend compile/execute failures degrade below.
        lowered = fused.lower(state, feats, vix, jax.random.PRNGKey(300))
        try:
            del lowered  # compile happens on first call
            state, m = fused(state, feats, vix, jax.random.PRNGKey(300))
            float(m["loss"])
            t0 = time.perf_counter()
            for i in range(args.steps):
                state, m = fused(state, feats, vix,
                                 jax.random.PRNGKey(301 + i))
            float(m["loss"])  # value fetch: trustworthy barrier (bench_xe)
            fused_cps = ncaps * args.steps / (time.perf_counter() - t0)
        except Exception as e:
            print(f"bench: fused device-reward execution failed ({e!r}); "
                  "reporting fused=null", file=sys.stderr)

    if want_fused and fused_cps is not None:
        value, path = fused_cps, "device_fused"
    elif want_fused and overlapped is not None:
        value, path = overlapped, "host_pipeline_fallback"
        print("bench: shipped default is --device_rewards 1 but the fused "
              "path did not execute; CST headline falls back to the host "
              "pipeline (cst_path=host_pipeline_fallback)", file=sys.stderr)
    elif want_fused:
        value, path = None, "device_fused"  # sweep point: fused only, died
    else:
        value, path = overlapped, "host_pipeline"
    # Early-exit step accounting (untimed; see rollout_step_probe).  A
    # probe failure must not sink the measured throughput above.
    probe_out = None
    if probe and dc > 0:
        try:
            probe_out = rollout_step_probe(model, state, feats, args, dc)
        except Exception as e:
            print(f"bench: rollout step probe failed ({e!r}); "
                  "reporting rollout_probe=null", file=sys.stderr)
    return {
        "value": value,
        "path": path,
        "host_pipeline_captions_per_sec":
            None if overlapped is None else round(overlapped, 1),
        "serial_captions_per_sec":
            None if serial is None else round(serial, 1),
        "fused_captions_per_sec":
            None if fused_cps is None else round(fused_cps, 1),
        "overlap_depth": depth,
        "scorer": scorer_kind,
        "decode_chunk": dc,
        "scan_unroll": axes["scan_unroll"],
        "decode_kernel": axes["decode_kernel"],
        "rollout_probe": probe_out,
    }


def bench_serving(args) -> dict:
    """Caption-serving probe (--stage serving): seeded open-loop Poisson
    arrivals through the continuous-batching engine at this run's bench
    shapes, EOS-biased like ``rollout_step_probe`` so the untrained bench
    model terminates captions the way a converged policy does.  Reports
    p50/p99 request latency + captions/s and ASSERTS 0 program builds
    after warmup (serving/bench.py) — the compile-discipline contract."""
    from cst_captioning_tpu.serving.bench import serving_probe
    from cst_captioning_tpu.serving.buckets import parse_buckets

    axes, _, _ = resolve_axes(args)
    model, state, _, _ = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16, scan_unroll=axes["scan_unroll"],
        decode_kernel=axes["decode_kernel"],
    )
    params = {**state.params}
    params["logit"] = {**params["logit"]}
    params["logit"]["bias"] = (
        params["logit"]["bias"].at[0].add(args.probe_eos_bias))
    probe_kw = dict(
        num_requests=args.serve_requests, rate_hz=args.serve_rate,
        max_len=args.seq_len, beam_size=args.serve_beam,
        decode_chunk=axes["decode_chunk"],
        bucket_sizes=parse_buckets(args.serve_buckets),
        queue_limit=0, seed=777,
        stream=bool(args.serve_stream),
        cache_size=args.serve_cache,
        unique_videos=args.serve_unique,
        zipf_alpha=args.serve_zipf,
        replicas=args.replicas,
        kill_replica=args.serve_kill_replica,
        arrival_shape=args.arrival_shape,
        arrival_trace=args.arrival_trace,
        lifecycle=bool(args.serve_trace or args.serve_blackbox),
        blackbox_path=args.serve_blackbox,
    )
    shapes = [(28, 2048), (1, 4096)]
    if args.serve_cache_compare and args.serve_cache:
        # A small UNMEASURED rehearsal first: the process's first probe
        # pays one-time warm-up (allocator/thread-pool first touch) that
        # would otherwise land on whichever measured run goes first and
        # fake a 2-3x gap between the twins.  Then the cache-OFF twin and
        # the cached probe at the SAME seed (identical arrival schedule
        # and zipfian mix) in the same bench run: the cached probe must
        # beat the twin on captions/s or the cache is not paying —
        # serve_report renders both and exits 1 when it doesn't.
        serving_probe(model, {"params": params}, shapes,
                      **{**probe_kw, "cache_size": 0, "num_requests": 8,
                         "rate_hz": min(args.serve_rate, 100.0),
                         "blackbox_path": None})
        twin = serving_probe(model, {"params": params}, shapes,
                             **{**probe_kw, "cache_size": 0,
                                "blackbox_path": None})
        out = serving_probe(model, {"params": params}, shapes, **probe_kw)
        out["cache_off_captions_per_sec"] = twin["captions_per_sec"]
        out["cache_off_latency_p50_ms"] = twin["latency_p50_ms"]
        if twin["captions_per_sec"] > 0:
            out["cache_speedup"] = round(
                out["captions_per_sec"] / twin["captions_per_sec"], 3)
    else:
        out = serving_probe(model, {"params": params}, shapes, **probe_kw)
    out["eos_bias"] = args.probe_eos_bias
    return out


def bench_data(args) -> dict:
    """Loader-only feed-rate probe (--stage data): the real prefetcher
    (data/loader.py prefetch_to_device) over an in-memory synthetic
    source with a declared simulated read latency — batches/s + caps/s
    drained flat out, plus data_wait share and queue occupancy at a
    simulated consumer rate (data/bench.py).  With --loader_workers > 1
    the single-worker twin runs at the SAME seed in the same bench run
    (after an unmeasured rehearsal, the serve_cache_compare discipline:
    first-probe warmup must not land on one twin and fake the gap), so
    the record carries the multi-worker speedup the data plane claims."""
    from cst_captioning_tpu.data.bench import feed_probe

    probe_kw = dict(
        batch_size=args.batch_size, seq_per_img=args.seq_per_img,
        seq_len=args.seq_len, vocab=args.vocab,
        num_videos=args.data_videos, workers=args.loader_workers,
        data_shards=args.data_shards, data_shard_id=args.data_shard_id,
        read_ms=args.data_read_ms,
        consumer_ms=args.data_consumer_ms or None,
        batches=args.data_batches, seed=777,
        # Deep enough that every worker can hold a ticket plus slack for
        # emission-order jitter; the occupancy gauge reports the actual.
        prefetch_size=max(4, args.loader_workers + 2),
    )
    out = None
    if args.data_compare and args.loader_workers > 1:
        feed_probe(**{**probe_kw, "workers": 1, "batches": 4})  # rehearsal
        twin = feed_probe(**{**probe_kw, "workers": 1})
        out = feed_probe(**probe_kw)
        out["single_worker_captions_per_sec"] = twin["captions_per_sec"]
        out["single_worker_batches_per_sec"] = twin["batches_per_sec"]
        out["single_worker_data_wait_share"] = twin["data_wait_share"]
        if twin["captions_per_sec"] > 0:
            out["workers_speedup"] = round(
                out["captions_per_sec"] / twin["captions_per_sec"], 3)
    else:
        feed_probe(**{**probe_kw, "batches": 4})  # rehearsal
        out = feed_probe(**probe_kw)
    return out


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--stage", default="both",
                   choices=("both", "xe", "cst", "serving", "data"),
                   help="'both' (default) measures XE and CST and reports "
                        "the MIN as the headline value — the driver artifact "
                        "cannot pass on the easy stage alone.  'serving' "
                        "runs the open-loop Poisson caption-serving probe "
                        "instead (serving/bench.py: p50/p99 request latency "
                        "+ captions/s through the continuous-batching "
                        "engine, 0 recompiles after warmup asserted).  "
                        "'data' runs the loader-only feed-rate probe "
                        "(data/bench.py: batches/s + caps/s out of the "
                        "real prefetcher, queue occupancy, data_wait "
                        "share at a simulated consumer rate) — the input-"
                        "path receipt against the 30k caps/s XE rate")
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_per_img", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=30)
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--bfloat16", type=int, default=1)
    p.add_argument("--overlap_depth", type=int, default=None,
                   help="CST reward-pipeline depth; default = the trainer's "
                        "--overlap_rewards default (read from opts.py); 0 "
                        "benches the strictly serial reference semantics")
    p.add_argument("--device_rewards", type=int, default=None,
                   help="which CST path is the headline: default = the "
                        "trainer's --device_rewards default (read from "
                        "opts.py, shipped 1 = fused on-device reward); 0 "
                        "headlines the host reward pipeline.  Both are "
                        "measured and reported either way")
    p.add_argument("--native_cider", type=int, default=1,
                   help="1 = C++ reward scorer (trainer default)")
    p.add_argument("--decode_chunk", type=int, default=None,
                   help="early-exit rollout chunk for the CST stage; "
                        "default = the trainer's resolved default (tuning "
                        "record, else opts.py); 0 benches the legacy "
                        "full-length scan")
    p.add_argument("--scan_unroll", type=int, default=None,
                   help="decoder-scan unroll for both stages; default = "
                        "the trainer's resolved default (tuning record, "
                        "else opts.py)")
    p.add_argument("--decode_kernel", default=None,
                   choices=("reference", "pallas", "bf16"),
                   help="decode-step cell for the CST rollout: the flax "
                        "reference cell, the fused Pallas decode kernel "
                        "(ops/pallas_decode_cell.py), or the bf16 "
                        "low-precision variant (ops/bf16_decode.py, "
                        "parity-gated); default = the trainer's resolved "
                        "default (tuning record, else 'reference')")
    p.add_argument("--serve_requests", type=int, default=24,
                   help="--stage serving: requests in the seeded Poisson "
                        "stream")
    p.add_argument("--serve_rate", type=float, default=8.0,
                   help="--stage serving: open-loop arrival rate (req/s)")
    p.add_argument("--serve_buckets", default="1,4,8",
                   help="--stage serving: batch-shape bucket ladder "
                        "(SERVING.md 'Bucket policy')")
    p.add_argument("--serve_beam", type=int, default=1,
                   help="--stage serving: beam width per request (1 = "
                        "greedy)")
    p.add_argument("--serve_stream", type=int, default=0,
                   help="--stage serving: 1 = submit every probe request "
                        "as streaming traffic — asserts prefix "
                        "consistency end to end and adds TTFT / "
                        "inter-chunk-gap percentiles to the JSON line")
    p.add_argument("--serve_cache", type=int, default=0,
                   help="--stage serving: exact-result cache capacity "
                        "(entries; 0 = off).  The probe keeps a hit-vs-"
                        "miss-twin drill record scripts/serve_report.py "
                        "gates on")
    p.add_argument("--serve_zipf", type=float, default=0.0,
                   help="--stage serving: zipf exponent for the request "
                        "mix over --serve_unique distinct videos (0 = "
                        "round-robin; real traffic is ~1.0-1.2)")
    p.add_argument("--serve_unique", type=int, default=None,
                   help="--stage serving: distinct videos in the request "
                        "mix (default: one per request — no repeats, the "
                        "historical probe)")
    p.add_argument("--replicas", type=int, default=1,
                   help="--stage serving: engine replicas behind the "
                        "fleet router (serving/fleet.py).  > 1 drives "
                        "the SAME seeded Poisson stream through the "
                        "health-aware router over N replicas sharing one "
                        "ProgramCache, reports caps/s/fleet, and runs a "
                        "fault-free single-engine reference decode whose "
                        "captions every fleet caption must match bit for "
                        "bit (serve_report gates on it).  1 = the "
                        "historical single-engine probe")
    p.add_argument("--serve_kill_replica", type=int, default=-1,
                   help="--stage serving with --replicas N: hard-kill "
                        "this replica once half the request stream is "
                        "submitted (its residents re-queue, the replica "
                        "restarts warm from the shared ProgramCache) — "
                        "the caps/s-under-replica-kill/restart drill.  "
                        "-1 = no kill")
    p.add_argument("--serve_cache_compare", type=int, default=0,
                   help="--stage serving: 1 = also run the cache-OFF twin "
                        "at the same seed in the same bench run and "
                        "report cache_off_captions_per_sec / "
                        "cache_speedup (requires --serve_cache > 0)")
    p.add_argument("--serve_trace", type=int, default=0,
                   help="--stage serving: 1 = arm the request-lifecycle "
                        "tracing plane (telemetry/lifecycle.py) — the "
                        "JSON line gains the terminal-accounting record "
                        "and the per-request latency attribution "
                        "(queue_wait/admit/decode/recovery/requeue "
                        "p50/p99, per replica), both gated by "
                        "scripts/serve_report.py.  0 (default) = "
                        "disarmed, the overhead-free measurement mode")
    p.add_argument("--serve_blackbox", default=None,
                   help="--stage serving: write the flight recorder's "
                        "blackbox.json here at probe end (implies "
                        "--serve_trace 1)")
    p.add_argument("--arrival_shape", default="poisson",
                   choices=("poisson", "diurnal", "burst", "replay"),
                   help="--stage serving: open-loop traffic model — "
                        "seeded Poisson (default), diurnal sinusoid, "
                        "square-wave burst storms, or JSONL trace "
                        "replay (serving/bench.make_arrivals)")
    p.add_argument("--arrival_trace", default=None,
                   help="--stage serving: JSONL arrival trace (one "
                        '{"t": seconds} per line) for '
                        "--arrival_shape replay")
    p.add_argument("--loader_workers", type=int, default=1,
                   help="--stage data: prefetch assembler threads "
                        "(--loader_workers in the trainer).  > 1 also "
                        "measures the single-worker twin in the same run "
                        "(disable with --data_compare 0) and reports "
                        "workers_speedup — the multi-worker data plane's "
                        "receipt")
    p.add_argument("--data_shards", type=int, default=0,
                   help="--stage data: shard count for the probe's "
                        "loader (0 = unsharded); the probe then feeds "
                        "from shard --data_shard_id of the global "
                        "epoch shuffle")
    p.add_argument("--data_shard_id", type=int, default=0,
                   help="--stage data: which shard the probe consumes")
    p.add_argument("--data_read_ms", type=float, default=10.0,
                   help="--stage data: simulated per-batch source read "
                        "latency (h5/NFS-shaped blocking IO; releases "
                        "the GIL like the real thing).  Default 10ms ~= "
                        "an ~8MB default-shape batch off a ~0.8GB/s "
                        "networked store.  Part of the probe's config "
                        "identity — the feed-rate claim is scoped to it "
                        "(PARITY.md 'Data-plane feed rate')")
    p.add_argument("--data_consumer_ms", type=float, default=0.0,
                   help="--stage data: simulated consumer step time for "
                        "the data_wait phase; 0 (default) = the per-"
                        "batch step time of a chip running XE at the "
                        "recorded 30k caps/s rate")
    p.add_argument("--data_batches", type=int, default=48,
                   help="--stage data: measured batches per phase")
    p.add_argument("--data_videos", type=int, default=64,
                   help="--stage data: videos in the synthetic source")
    p.add_argument("--data_compare", type=int, default=1,
                   help="--stage data: 1 (default) = also measure the "
                        "single-worker twin at the same seed when "
                        "--loader_workers > 1, reporting "
                        "single_worker_captions_per_sec and "
                        "workers_speedup (scripts/data_report.py gates "
                        "on >= 2x at 4 workers)")
    p.add_argument("--probe_eos_bias", type=float, default=10.0,
                   help="EOS-logit bias for the rollout step-count probe "
                        "(simulates a converged policy's early "
                        "termination; see rollout_step_probe).  Does not "
                        "affect the measured throughput numbers")
    p.add_argument("--cache", type=int, default=1,
                   help="0 = do not persist this run to BENCH_TPU_CACHE "
                        "(exploratory configs must not clobber the "
                        "shipped-config entry the CPU fallback attaches)")
    p.add_argument("--platform", default="auto", choices=("auto", "device", "cpu"),
                   help="auto: probe the default backend, fall back to cpu; "
                        "device: require the probed backend; cpu: host only")
    p.add_argument("--probe_timeout", type=float, default=120.0,
                   help="seconds before one backend-init probe is declared wedged")
    p.add_argument("--probe_retries", type=int, default=2)
    p.add_argument("--probe_backoff", type=float, default=2.0,
                   help="seconds of linear backoff between backend-probe "
                        "retries (attempt N waits N*backoff); the retry "
                        "record + per-attempt latencies land in the JSON "
                        "line's 'probe' field, labeled kind=probe_error/"
                        "probe_timeout when every attempt failed")
    p.add_argument("--child_timeout", type=float, default=1800.0,
                   help="seconds for ONE measurement child process (a "
                        "wedge-mid-measurement worst case pays this twice: "
                        "device attempt + CPU-fallback rerun)")
    return p.parse_args()


TPU_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_TPU_CACHE.json")


def _git_sha() -> str:
    from cst_captioning_tpu.utils.platform import git_head_sha

    return git_head_sha(os.path.dirname(os.path.abspath(__file__)))


def read_cache_entry(metric: str):
    """Last cached device measurement for ``metric``, or None (missing
    file, bad JSON, unknown metric) — shared by _emit's CPU-fallback
    attach and last_resort_emit so the cache schema has ONE reader."""
    try:
        with open(TPU_CACHE) as f:
            return json.load(f).get("entries", {}).get(metric)
    except (OSError, ValueError):
        return None


def resolved_config(args) -> dict:
    """The perf-affecting configuration identity of a run, with the
    follow-the-trainer-default flags (None) normalized to their RESOLVED
    values — flag > tuning record > built-in, via ``resolve_axes`` — so
    `bench.py` and `bench.py --device_rewards 1` (the same measured
    configuration) share a cache entry, and a tuned-default run and the
    same config passed as explicit flags share one too.  This identity is
    also what the tuning record's sweep points are keyed by.

    "steps" is deliberately NOT part of the identity: it sets averaging
    length, not what is measured — and the CPU fallback trims it (see
    run_measurement) without forfeiting the cache attach."""
    from cst_captioning_tpu.opts import DEFAULT_REMAT_CELL

    axes, _, _ = resolve_axes(args)
    config = {k: getattr(args, k) for k in
              ("batch_size", "seq_per_img", "seq_len", "vocab", "hidden",
               "bfloat16", "native_cider")}
    config["overlap_depth"] = axes["overlap_rewards"]
    config["device_rewards"] = axes["device_rewards"]
    config["decode_chunk"] = axes["decode_chunk"]
    config["scan_unroll"] = axes["scan_unroll"]
    config["decode_kernel"] = axes["decode_kernel"]
    # build() bakes this model-level default into the measured program,
    # so it is part of the configuration identity too.
    config["remat_cell"] = DEFAULT_REMAT_CELL
    if getattr(args, "stage", None) == "serving":
        # Serving-probe identity axes (its cache entry lives under its own
        # metric key; training-stage entries keep their historical shape).
        config["serve_requests"] = args.serve_requests
        config["serve_rate"] = args.serve_rate
        config["serve_buckets"] = args.serve_buckets
        config["serve_beam"] = args.serve_beam
        # Latency-floor axes (streamed emission, the result cache, and
        # the request mix all change what a latency number means).
        config["serve_stream"] = args.serve_stream
        config["serve_cache"] = args.serve_cache
        config["serve_zipf"] = args.serve_zipf
        config["serve_unique"] = args.serve_unique
        # compare mode changes the measurement protocol (unmeasured
        # rehearsal before the measured probe), so records from the two
        # modes are not comparable and must not share a cache entry.
        config["serve_cache_compare"] = args.serve_cache_compare
        # Fleet axes: a caps/s/fleet number over N replicas (and one
        # measured through a mid-stream replica kill) must never share
        # a cache entry with a single-engine record.
        config["replicas"] = args.replicas
        config["serve_kill_replica"] = args.serve_kill_replica
        # The traffic model shapes every latency number (a burst-storm
        # p99 is not a Poisson p99): part of the identity.  Absent on
        # pre-arrival-shape arg namespaces = the historical Poisson.
        config["arrival_shape"] = getattr(args, "arrival_shape",
                                          "poisson")
        # Lifecycle tracing adds per-event host work to the measured
        # path: a traced record and an untraced one are different
        # measurement protocols and must not share a cache entry.
        config["serve_trace"] = int(bool(
            getattr(args, "serve_trace", 0)
            or getattr(args, "serve_blackbox", None)))
    if getattr(args, "stage", None) == "data":
        # Data-plane feed-probe identity (ISSUE 15): worker count, shard
        # assignment, simulated source latency, consumer pacing, and the
        # compare protocol all change what the feed rate means — none may
        # share a cache entry across values.
        config["loader_workers"] = args.loader_workers
        config["data_shards"] = args.data_shards
        config["data_shard_id"] = args.data_shard_id
        config["data_read_ms"] = args.data_read_ms
        config["data_consumer_ms"] = args.data_consumer_ms
        config["data_batches"] = args.data_batches
        config["data_videos"] = args.data_videos
        config["data_compare"] = args.data_compare
    return config


def _emit(result: dict, args) -> None:
    """Print the ONE JSON line; persist real-device results to the cache,
    and on a CPU fallback attach the last cached device measurement
    (clearly labeled with its timestamp) so a wedged TPU tunnel degrades
    to 'CPU number + last known TPU number' instead of CPU-only.

    The cache is keyed by metric (a --stage xe run cannot clobber the
    full-bench headline entry) and records every perf-affecting flag; an
    entry is only attached when the current run's metric AND config
    match, so a cached result from a different configuration can never
    masquerade as comparable to this run's headline."""
    config = resolved_config(args)
    metric = result.get("metric")
    if result.get("platform") != "cpu":
        if not args.cache:  # exploratory config: measured, not persisted
            print(json.dumps(result))
            return
        cache = {}
        try:
            if os.path.exists(TPU_CACHE):
                with open(TPU_CACHE) as f:
                    cache = json.load(f)
            if "entries" not in cache:
                cache = {"entries": {}}
            cache["entries"][metric] = {
                "measured_at": time.strftime("%Y-%m-%d %H:%M:%S"),
                # The SHA pins which code produced the cached number, so a
                # reader can diff the measured tree against HEAD instead
                # of taking the repo's word for it.
                "git_sha": _git_sha(),
                # steps rides along informationally (averaging length of
                # the cached measurement) without joining the identity.
                "steps": args.steps,
                "config": config, "result": result,
            }
            atomic_json_write(TPU_CACHE, cache, indent=2)
        except (OSError, ValueError):
            pass
    else:
        entry = read_cache_entry(metric)
        if entry is not None and entry.get("config") == config:
            result = {**result, "last_tpu_result": entry}
    print(json.dumps(result))


def run_measurement(args) -> None:
    """Measure in THIS process (assumes a live jax backend) and print JSON.

    The benched steps run under plain jax.jit on ONE device, so the
    measured throughput already is per-chip — DP scales it linearly
    (tests/test_parallel.py proves step equivalence across the mesh).
    """
    import jax

    platform = jax.devices()[0].platform
    if platform == "cpu" and args.platform == "auto" and args.steps > 5:
        # Trim only the FALLBACK case (--platform auto that landed on the
        # host CPU); an explicit --platform cpu run keeps its requested
        # step count.
        # The fallback CPU number is a shape-check, not a throughput claim
        # (the JSON says platform=cpu and the real TPU entry rides along
        # from the cache); full-shape CPU measurement at the default step
        # count runs >25 min and can outlive the driver's timeout, which
        # would mean NO artifact at all.
        print(f"bench: CPU fallback trims --steps {args.steps} -> 5",
              file=sys.stderr)
        args.steps = 5
    device_kind = getattr(jax.devices()[0], "device_kind", "")
    ncaps = args.batch_size * args.seq_per_img
    flops = analytic_step_flops(args)
    common = {
        "unit": "captions/s/chip",
        "platform": platform,
        "num_devices": jax.device_count(),
        # Landed on the host CPU while a device was WANTED (probe failed /
        # device child died) — explicit, instead of implied by "platform".
        "cpu_fallback": (platform == "cpu"
                         and os.environ.get("_BENCH_CPU_FALLBACK") == "1"),
        # Tuned-config provenance (ISSUE 6): "tuned" says whether any axis
        # resolved from the platform's tuning record; rides into the cache
        # entry too, so a hand-flagged measurement can never be mistaken
        # for a tuned one.
        **tuning_fields(args),
    }
    # Backend-probe telemetry from the parent (attempt latencies, timeout
    # count — satellite of ISSUE 2): the parent probes, the child
    # measures, so the record crosses via env.
    probe_json = os.environ.get("_BENCH_PROBE_JSON")
    if probe_json:
        try:
            common["probe"] = json.loads(probe_json)
        except ValueError:
            pass
    if args.stage == "data":
        from cst_captioning_tpu.data.bench import XE_CHIP_CAPS_PER_SEC

        data = bench_data(args)
        _emit({
            "metric": HEADLINE_METRIC["data"],
            "value": data["captions_per_sec"],
            # The honest ratio for a FEED rate is the demand it must
            # cover: the recorded peak on-chip XE consumption rate —
            # >= 1.0 means the input path can keep a chip fed at the
            # fastest rate the compute path has ever demanded.  Not the
            # 5000-caps/s training north-star (that measures compute).
            "vs_baseline": data["vs_xe_rate"],
            **common,
            # AFTER **common: a host-side feed rate is captions/s out of
            # the loader, not captions/s/chip.
            "unit": "captions/s",
            **{k: v for k, v in data.items() if k != "captions_per_sec"},
            "xe_rate_baseline": XE_CHIP_CAPS_PER_SEC,
        }, args)
        return
    if args.stage == "serving":
        serve = bench_serving(args)
        _emit({
            "metric": HEADLINE_METRIC["serving"],
            "value": serve["captions_per_sec"],
            # The 5000 caps/s north-star is a TRAINING-throughput target;
            # an open-loop probe is capped by its arrival rate, so a ratio
            # against it would read as a fake catastrophic regression.
            # Serving has no baseline yet: null, honestly.
            "vs_baseline": None,
            **common,
            **{k: v for k, v in serve.items() if k != "captions_per_sec"},
        }, args)
        return
    if args.stage == "xe":
        xe = bench_xe(args)
        _emit({
            "metric": HEADLINE_METRIC["xe"],
            "value": round(xe, 1),
            "vs_baseline": round(xe / BASELINE_CAPTIONS_PER_SEC, 3),
            **common,
            **mfu_fields(flops["xe"], xe, ncaps, device_kind),
        }, args)
        return
    if args.stage == "cst":
        cst = bench_cst(args)
        _emit({
            "metric": HEADLINE_METRIC["cst"],
            "value": round(cst["value"], 1),
            "vs_baseline": round(cst["value"] / BASELINE_CAPTIONS_PER_SEC, 3),
            **common,
            **{k: v for k, v in cst.items() if k != "value"},
            **mfu_fields(flops["cst"], cst["value"], ncaps, device_kind),
        }, args)
        return
    # default: BOTH stages, headline = the worse of the two, so the driver
    # artifact can never pass on the easy stage alone (VERDICT.md round 2).
    xe = bench_xe(args)
    cst = bench_cst(args)
    worst = min(xe, cst["value"])
    xe_mfu = mfu_fields(flops["xe"], xe, ncaps, device_kind)
    cst_mfu = mfu_fields(flops["cst"], cst["value"], ncaps, device_kind)
    _emit({
        "metric": HEADLINE_METRIC["both"],
        "value": round(worst, 1),
        "vs_baseline": round(worst / BASELINE_CAPTIONS_PER_SEC, 3),
        **common,
        "xe_captions_per_sec": round(xe, 1),
        "cst_captions_per_sec": round(cst["value"], 1),
        "cst_path": cst["path"],
        "cst_host_pipeline_captions_per_sec":
            cst["host_pipeline_captions_per_sec"],
        "cst_serial_captions_per_sec": cst["serial_captions_per_sec"],
        "cst_fused_captions_per_sec": cst["fused_captions_per_sec"],
        "cst_overlap_depth": cst["overlap_depth"],
        "cst_scorer": cst["scorer"],
        "cst_decode_chunk": cst["decode_chunk"],
        "cst_scan_unroll": cst["scan_unroll"],
        "cst_decode_kernel": cst["decode_kernel"],
        "cst_rollout_probe": cst["rollout_probe"],
        **{f"xe_{k}": v for k, v in xe_mfu.items()},
        **{f"cst_{k}": v for k, v in cst_mfu.items()},
    }, args)


def probe_backend(timeout_s: float, retries: int,
                  backoff_s: float = 0.0) -> tuple[str | None, dict]:
    """Initialize the default jax backend in a throwaway subprocess.

    Returns ``(platform, probe_info)``: the platform string (None if every
    attempt failed or timed out — a downed remote-TPU tunnel blocks
    *inside* backend init, so the probe, not the measurement, is what must
    absorb the hang) plus a telemetry record of every attempt.
    ``probe_info`` rides into the emitted JSON so three silent 120s
    timeouts (BENCH_r05) become an auditable
    ``{"kind": "probe_error", "attempts": [...], "timeouts": 3}`` — a
    machine-readable diagnostic for the chip-window regression — instead
    of stderr-only noise.  Retries back off linearly (``backoff_s``,
    ``2 * backoff_s``, ...): a tunnel mid-reconnect gets a window to come
    back instead of three instant identical failures.

    The probe child runs in its own process group with output to temp
    files, not pipes: a wedged PJRT plugin can spawn helper processes that
    inherit captured pipes and would keep them open past the child's own
    kill, turning ``subprocess.run(capture_output=True)``'s post-timeout
    drain into a second, unbounded hang.
    """
    import signal
    import tempfile

    info: dict = {"attempts": [], "timeouts": 0, "timeout_s": timeout_s,
                  "backoff_s": backoff_s}

    def done(outcome: str, t0: float, platform: str | None = None):
        rec = {"outcome": outcome,
               "latency_s": round(time.perf_counter() - t0, 3)}
        if platform is not None:
            rec["platform"] = platform
        info["attempts"].append(rec)

    def backoff(attempt: int) -> None:
        if backoff_s > 0 and attempt < retries:
            wait = backoff_s * (attempt + 1)
            info["attempts"][-1]["backoff_s"] = wait
            print(f"bench: backing off {wait:.1f}s before probe retry",
                  file=sys.stderr)
            time.sleep(wait)

    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    for attempt in range(retries + 1):
        t0 = time.perf_counter()
        with tempfile.TemporaryFile("w+") as out, \
                tempfile.TemporaryFile("w+") as err:
            proc = subprocess.Popen(
                [sys.executable, "-c", code],
                stdout=out, stderr=err, text=True, start_new_session=True,
            )
            try:
                proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    proc.kill()
                proc.wait()
                done("timeout", t0)
                info["timeouts"] += 1
                print(f"bench: backend probe timed out ({timeout_s:.0f}s), "
                      f"attempt {attempt + 1}/{retries + 1}", file=sys.stderr)
                backoff(attempt)
                continue
            out.seek(0)
            for line in out.read().splitlines():
                if line.startswith("PLATFORM="):
                    plat = line.split("=", 1)[1].strip()
                    done("ok", t0, plat)
                    return plat, info
            err.seek(0)
            done("error", t0)
            print(f"bench: backend probe rc={proc.returncode}, attempt "
                  f"{attempt + 1}/{retries + 1}\n{err.read()[-2000:]}",
                  file=sys.stderr)
            backoff(attempt)
    # Total probe failure: label the record so the bench JSON carries a
    # classified, machine-auditable diagnostic (not just a cpu_fallback
    # flag a reader has to interpret).
    info["kind"] = ("probe_timeout" if info["timeouts"] == len(
        info["attempts"]) else "probe_error")
    return None, info


def spawn_child(scrub: bool, timeout_s: float,
                extra_env: dict | None = None) -> tuple[int, bool]:
    """Re-exec this script for the measurement; returns (rc, emitted).

    Runs in its own process group (see run_in_group) so that if the device
    path wedges mid-measurement, killing it also kills any tunnel helper
    processes before the CPU-fallback rerun.

    The child's stdout is captured to a temp FILE (pipe-safe across the
    group kill) and relayed verbatim, so the parent can tell whether the
    child actually printed its JSON line — the input to main()'s
    last-resort emit when every measurement attempt dies.
    """
    import tempfile

    from cst_captioning_tpu.utils.platform import run_in_group, scrub_env

    env = dict(os.environ)
    env["_BENCH_CHILD"] = "1"
    if extra_env:
        env.update(extra_env)
    if scrub:
        scrub_env(env)
        env["PYTHONPATH"] = ""  # drop any sitecustomize (e.g. .axon_site)
    with tempfile.TemporaryFile("w+") as out:
        rc = run_in_group(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env, cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=timeout_s, stdout=out,
        )
        out.seek(0)
        captured = out.read()
    emitted = False
    for line in captured.splitlines():
        try:
            emitted = emitted or "metric" in json.loads(line)
        except (ValueError, TypeError):
            # TypeError: the line parsed to a JSON scalar ("42", "null")
            pass
    sys.stdout.write(captured)
    sys.stdout.flush()
    if rc == 124:
        print(f"bench: measurement child timed out ({timeout_s:.0f}s)",
              file=sys.stderr)
    return rc, emitted


HEADLINE_METRIC = {
    "xe": "xe_captions_per_sec_per_chip",
    "cst": "cst_captions_per_sec_per_chip",
    "both": "min_xe_cst_captions_per_sec_per_chip",
    "serving": "serve_captions_per_sec_per_chip",
    "data": "data_feed_captions_per_sec",
}


def last_resort_emit(args, child_rc: int, reason: str,
                     probe: dict | None = None) -> None:
    """Final line of defense for the one-JSON-line contract: every exit
    path of main() must print exactly one parseable line, even when the
    device backend is wedged AND the CPU-fallback child itself died or
    outlived --child_timeout (round-3 judge repro: exit 124, no JSON).

    value=null + platform="none" says honestly that nothing was measured
    this run; the last cached device measurement (with its own config and
    timestamp) rides along so the artifact still carries the freshest
    hardware number available.
    """
    metric = HEADLINE_METRIC[args.stage]
    result = {
        "metric": metric,
        "value": None,
        "vs_baseline": None,
        "unit": "captions/s/chip",
        "platform": "none",
        "child_rc": child_rc,
        "error": reason,
    }
    if probe is not None:
        result["probe"] = probe
    entry = read_cache_entry(metric)
    if entry is not None:
        result["last_tpu_result"] = entry
        # Unlike _emit's CPU-fallback attach, the entry rides along even
        # when this run's shapes differ (there is no fresher number to
        # prefer) — but labeled, so a consumer can't read a full-shape
        # cached number as comparable to a tiny-shape wedged run without
        # noticing.
        result["last_tpu_config_matches"] = (
            entry.get("config") == resolved_config(args))
    print(json.dumps(result))


def main():
    args = parse_args()

    if os.environ.get("_BENCH_CHILD") == "1":
        run_measurement(args)
        return

    use_device = False
    probe_info = None
    cpu_fallback = False
    if args.platform in ("auto", "device"):
        plat, probe_info = probe_backend(args.probe_timeout,
                                         args.probe_retries,
                                         backoff_s=args.probe_backoff)
        if plat is not None and plat != "cpu":
            use_device = True
        elif args.platform == "device":
            last_resort_emit(args, -1, "--platform device but the default "
                             f"backend is {plat!r} after "
                             f"{args.probe_retries + 1} probes",
                             probe=probe_info)
            sys.exit(EXIT_FAILURE)
        elif plat == "cpu":
            print("bench: default backend is the host CPU; measuring there",
                  file=sys.stderr)
        else:
            cpu_fallback = True  # device wanted, probe never answered
            print("bench: default backend unreachable, falling back to host "
                  "CPU (JSON will say platform=cpu, cpu_fallback=true)",
                  file=sys.stderr)

    def child_env(fallback: bool) -> dict:
        env = {"_BENCH_CPU_FALLBACK": "1" if fallback else "0"}
        if probe_info is not None:
            env["_BENCH_PROBE_JSON"] = json.dumps(probe_info)
        return env

    rc, emitted = spawn_child(scrub=not use_device,
                              timeout_s=args.child_timeout,
                              extra_env=child_env(cpu_fallback))
    if rc != 0 and not emitted and use_device and args.platform == "auto":
        # Device path died mid-measurement (tunnel dropped?) before printing
        # its JSON line — still emit a well-formed line rather than nothing.
        # (A child that printed its line and THEN died nonzero must not be
        # re-run: two JSON lines would break the one-line contract.)
        print("bench: device measurement failed, retrying on host CPU",
              file=sys.stderr)
        rc, emitted = spawn_child(scrub=True, timeout_s=args.child_timeout,
                                  extra_env=child_env(True))
    if not emitted:
        # The last measurement child died or timed out without printing —
        # the one case round 3 shipped without cover.  Emit the degraded
        # artifact; the line itself says no measurement happened.  Exit 0
        # only for --platform auto (graceful degradation is its designed
        # behavior); an explicitly-required platform that measured nothing
        # is a failure, matching the probe-failure path above.
        last_resort_emit(
            args, rc,
            "measurement child produced no JSON "
            + ("(timed out)" if rc == 124 else f"(rc={rc})"),
            probe=probe_info)
        sys.exit(EXIT_OK if args.platform == "auto" else EXIT_FAILURE)
    sys.exit(rc)


if __name__ == "__main__":
    main()
