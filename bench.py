#!/usr/bin/env python
"""Throughput benchmark — captions/sec/chip on the XE train step.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "captions/s/chip", "vs_baseline": N}

Baseline: the driver north-star of >= 5000 captions/sec/chip for the XE and
CST stages on MSR-VTT-shaped data (BASELINE.md; the reference published no
throughput numbers — SURVEY.md §6).  ``vs_baseline`` is value/5000.

Shapes mirror MSR-VTT training: ResNet-152 (28, 2048) + C3D (1, 4096)
features, vocab ~8k, 30-token captions, 20 captions/video, attention-LSTM
decoder (hidden 512).  Data is synthetic and device-resident so the number
measures the compiled step, not disk IO (the loader's prefetch thread hides
IO in real training; see cst_captioning_tpu/data/loader.py).

Flags: --stage xe|cst benches the XE step or the full CST iteration
(rollout + host CIDEr-D reward + REINFORCE grad step).
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

import jax
import jax.numpy as jnp

BASELINE_CAPTIONS_PER_SEC = 5000.0


def build(batch: int, seq_per_img: int, seq_len: int, vocab: int,
          hidden: int, use_bfloat16: bool):
    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.training.state import create_train_state, make_optimizer

    model = CaptionModel(
        vocab_size=vocab, embed_size=hidden, hidden_size=hidden,
        attn_size=hidden, use_attention=True, dropout_rate=0.5,
        dtype=jnp.bfloat16 if use_bfloat16 else jnp.float32,
    )
    tx, _ = make_optimizer(learning_rate=2e-4, grad_clip=10.0)
    feat_shapes = [(28, 2048), (1, 4096)]
    state = create_train_state(
        model, jax.random.PRNGKey(0), feat_shapes, seq_len, seq_per_img, tx,
        batch_size=batch,
    )
    rng = np.random.default_rng(0)
    feats = [
        jnp.asarray(rng.standard_normal((batch, t, d)), jnp.float32)
        for t, d in feat_shapes
    ]
    labels = jnp.asarray(
        rng.integers(1, vocab, (batch * seq_per_img, seq_len)), jnp.int32
    )
    # realistic 0-termination: captions average ~10 tokens
    lens = rng.integers(6, seq_len - 1, batch * seq_per_img)
    labels = jnp.asarray(np.where(
        np.arange(seq_len)[None, :] < lens[:, None], np.asarray(labels), 0
    ), jnp.int32)
    return model, state, feats, labels


def bench_xe(args):
    from cst_captioning_tpu.training.steps import make_xe_step

    model, state, feats, labels = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16,
    )
    weights = jnp.ones((args.batch_size * args.seq_per_img,))
    step = jax.jit(make_xe_step(model, args.seq_per_img), donate_argnums=(0,))
    rng = jax.random.PRNGKey(0)

    state, m = step(state, feats, labels, weights, rng)       # compile
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for _ in range(args.steps):
        state, m = step(state, feats, labels, weights, rng)
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    return args.batch_size * args.seq_per_img * args.steps / dt


def bench_cst(args):
    from cst_captioning_tpu.data.vocab import Vocab
    from cst_captioning_tpu.metrics.ciderd import CiderD, build_corpus_df
    from cst_captioning_tpu.training.rewards import RewardComputer
    from cst_captioning_tpu.training.steps import make_rl_grad_step, make_rollout

    model, state, feats, labels = build(
        args.batch_size, args.seq_per_img, args.seq_len, args.vocab,
        args.hidden, args.bfloat16,
    )
    vocab = Vocab({i: f"w{i}" for i in range(1, args.vocab)})
    # synthetic reference corpus: 20 refs per video, ~10 tokens each
    rng = np.random.default_rng(1)
    refs = {
        f"v{i}": [
            " ".join(f"w{w}" for w in rng.integers(1, args.vocab, 10))
            for _ in range(20)
        ]
        for i in range(args.batch_size)
    }
    df, n = build_corpus_df(refs)
    scorer = CiderD(df_mode="corpus", df=df, ref_len=float(n))
    rc = RewardComputer(vocab, scorer, refs, seq_per_img=args.seq_per_img,
                        baseline="greedy")
    video_ids = list(refs.keys())

    rollout = jax.jit(make_rollout(model, args.seq_len, args.seq_per_img))
    rl_step = jax.jit(make_rl_grad_step(model, args.seq_per_img),
                      donate_argnums=(0,))

    def one_iter(state, key):
        sampled, greedy = rollout(state.params, feats, key)
        s = np.asarray(jax.device_get(sampled))
        g = np.asarray(jax.device_get(greedy))
        adv, _ = rc(video_ids, s, g)
        state, m = rl_step(state, feats, sampled, jnp.asarray(adv), key)
        return state, m

    state, m = one_iter(state, jax.random.PRNGKey(0))          # compile
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    for i in range(args.steps):
        state, m = one_iter(state, jax.random.PRNGKey(i + 1))
    jax.block_until_ready(m["loss"])
    dt = time.perf_counter() - t0
    return args.batch_size * args.seq_per_img * args.steps / dt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--stage", default="xe", choices=("xe", "cst"))
    p.add_argument("--batch_size", type=int, default=32)
    p.add_argument("--seq_per_img", type=int, default=20)
    p.add_argument("--seq_len", type=int, default=30)
    p.add_argument("--vocab", type=int, default=8000)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--bfloat16", type=int, default=1)
    args = p.parse_args()

    cps = bench_xe(args) if args.stage == "xe" else bench_cst(args)
    # The benched step runs under plain jax.jit on ONE device, so the
    # measured throughput already is per-chip — DP scales it linearly
    # (tests/test_parallel.py proves step equivalence across the mesh).
    per_chip = cps
    print(json.dumps({
        "metric": f"{args.stage}_captions_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "captions/s/chip",
        "vs_baseline": round(per_chip / BASELINE_CAPTIONS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
