#!/usr/bin/env python
"""Score an arbitrary predictions JSON against references — no model needed.

The reference's ``standalone_eval.py`` equivalent (SURVEY.md §2): accepts
either a bare list of {"image_id", "caption"} or the {"predictions": [...]}
wrapper eval.py writes, plus a coco-format annotations file.

  python standalone_eval.py predictions.json refs_cocofmt.json [-o scores.json]
"""

from __future__ import annotations

import argparse
import json
import sys

from cst_captioning_tpu.metrics.coco_eval import language_eval
from cst_captioning_tpu.resilience.integrity import atomic_json_write


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("predictions")
    p.add_argument("references", help="coco-format annotations JSON")
    p.add_argument("-o", "--output", default=None)
    args = p.parse_args(argv)

    with open(args.predictions) as f:
        preds = json.load(f)
    if isinstance(preds, dict):
        preds = preds["predictions"]
    scores = language_eval(preds, args.references)
    print(json.dumps(scores, indent=2))
    if args.output:
        atomic_json_write(args.output, scores, indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
